"""Hessian max-eigenvalue estimation by power iteration.

Reference: ``deepspeed/runtime/eigenvalue.py:7`` — used by MoQ to scale each
layer's quantization period by its loss-curvature. The torch version
re-runs autograd per iteration with retained graphs; the JAX version is a
jitted Hessian-vector-product power iteration (``jax.jvp`` of ``jax.grad``),
which XLA compiles once — double-backward for free.
"""

from typing import Any, Callable, Dict

import jax
import jax.numpy as jnp


def _normalize(tree):
    norm = jnp.sqrt(sum(jnp.sum(jnp.square(x))
                        for x in jax.tree_util.tree_leaves(tree)))
    norm = jnp.maximum(norm, 1e-12)
    return jax.tree_util.tree_map(lambda x: x / norm, tree), norm


class Eigenvalue:
    """Power-iteration estimate of the largest |eigenvalue| of the Hessian
    of ``loss_fn`` w.r.t. each top-level param subtree (per-layer, as the
    reference iterates per block)."""

    def __init__(self, verbose: bool = False, max_iter: int = 100,
                 tol: float = 1e-2, stability: float = 1e-6,
                 gas_boundary_resolution: int = 1,
                 layer_name: str = "", layer_num: int = 0):
        self.verbose = verbose
        self.max_iter = int(max_iter)
        self.tol = float(tol)
        self.stability = float(stability)
        self.gas_boundary_resolution = int(gas_boundary_resolution)
        self.layer_name = layer_name
        self.layer_num = layer_num

    def compute_eigenvalue(self, loss_fn: Callable, params: Any, batch: Any,
                           rng=None) -> Dict[str, float]:
        """Per-top-level-subtree max |eigenvalue|.

        ``loss_fn(params, batch, rng) -> loss`` (the engine's convention).
        """
        if rng is None:
            rng = jax.random.PRNGKey(0)

        def scalar_loss(p):
            out = loss_fn(p, batch, rng)
            return (out[0] if isinstance(out, tuple) else out).astype(
                jnp.float32)

        grad_fn = jax.grad(scalar_loss)

        def hvp(p, v):
            return jax.jvp(grad_fn, (p,), (v,))[1]

        def power_iterate(p, key):
            v = jax.tree_util.tree_map(
                lambda x: jax.random.normal(
                    jax.random.fold_in(key, hash(x.shape) % (2 ** 31)),
                    x.shape, jnp.float32), p)
            v, _ = _normalize(v)

            def body(carry, _):
                v, _ = carry
                hv = hvp(p, v)
                v, lam = _normalize(hv)
                return (v, lam), lam

            (_, lam), _ = jax.lax.scan(body, (v, jnp.float32(0.0)), None,
                                       length=self.max_iter)
            return lam

        results: Dict[str, float] = {}
        if isinstance(params, dict):
            keys = list(params)
            for i, name in enumerate(keys):
                sub = params[name]

                def sub_loss(s, name=name):
                    merged = dict(params)
                    merged[name] = s
                    return scalar_loss(merged)

                g = jax.grad(sub_loss)

                # jit once per subtree; the up-to-max_iter iterations then
                # reuse the compiled double-backward (no re-tracing).
                sub_hvp = jax.jit(
                    lambda v, g=g, sub=sub: jax.jvp(g, (sub,), (v,))[1])

                key = jax.random.fold_in(rng, i)
                v = jax.tree_util.tree_map(
                    lambda x: jax.random.normal(
                        jax.random.fold_in(key, abs(hash(str(x.shape))) %
                                           (2 ** 31)), x.shape, jnp.float32),
                    sub)
                v, _ = _normalize(v)
                lam = jnp.float32(0.0)
                for _ in range(self.max_iter):
                    hv = sub_hvp(v)
                    v, new_lam = _normalize(hv)
                    if abs(float(new_lam) - float(lam)) <= self.tol * max(
                            abs(float(lam)), 1e-12):
                        lam = new_lam
                        break
                    lam = new_lam
                results[name] = max(float(lam), self.stability)
        else:
            results["model"] = max(float(power_iterate(params, rng)),
                                   self.stability)
        if self.verbose:
            from deepspeed_tpu.utils.logging import logger
            logger.info(f"eigenvalues: {results}")
        return results

    def max_eigenvalue(self, loss_fn, params, batch, rng=None) -> float:
        vals = self.compute_eigenvalue(loss_fn, params, batch, rng)
        return max(vals.values())
