"""CSR-style sparse tensor (API parity with reference csr_tensor.py).

Reference: ``deepspeed/runtime/csr_tensor.py:11`` — compressed row-sparse
gradients for huge embedding tables, reduced rank-to-rank by exchanging
(indices, values) instead of the dense table
(``runtime/engine.py:1530-1586`` sparse_allreduce).

TPU note: torch's sparse embedding autograd emits genuinely sparse
gradients; XLA's AD always materializes dense cotangents, so the engine
cannot re-compress them behind the user's back. The capability lives one
level down instead: ``sparse_gradients: true`` makes the in-tree
families' ``ops/embedding.embedding_lookup`` use a custom VJP whose
cross-rank exchange all_gathers (ids, touched rows) over the data axes
(``comm/sparse.py row_sparse_allreduce``) and scatter-adds locally —
wire bytes ∝ batch tokens, and the dense [V, D] buffer never crosses the
wire (tests/test_sparse_grads.py). A custom loss_fn still gets a loud
ConfigError pointing at ``sparse_grad_axes``. The utility below is
provided for API/tooling parity (checkpoint surgery, host-side gradient
analysis) with the reference's semantics (sparse row dedup on
``to_dense``).
"""

from typing import Tuple

import numpy as np


class CsrTensor:
    """Row-sparse [N, D] tensor: ``indices`` [nnz] row ids (may repeat —
    duplicates sum on densify, matching torch sparse semantics),
    ``values`` [nnz, D]."""

    def __init__(self, indices: np.ndarray, values: np.ndarray,
                 dense_shape: Tuple[int, int]):
        self.indices = np.asarray(indices, np.int64)
        self.values = np.asarray(values)
        self.dense_shape = tuple(dense_shape)
        if self.values.shape[0] != self.indices.shape[0]:
            raise ValueError("indices/values leading dims differ")

    @classmethod
    def from_dense(cls, dense: np.ndarray) -> "CsrTensor":
        dense = np.asarray(dense)
        rows = np.flatnonzero(np.any(dense != 0, axis=tuple(
            range(1, dense.ndim))))
        return cls(rows, dense[rows], dense.shape)

    def to_dense(self) -> np.ndarray:
        out = np.zeros(self.dense_shape, self.values.dtype)
        np.add.at(out, self.indices, self.values)
        return out

    @property
    def nnz(self) -> int:
        return int(self.indices.shape[0])

    @property
    def sparsity(self) -> float:
        return 1.0 - self.nnz / max(self.dense_shape[0], 1)

    def scale(self, s: float) -> "CsrTensor":
        return CsrTensor(self.indices, self.values * s, self.dense_shape)

    def add(self, other: "CsrTensor") -> "CsrTensor":
        if other.dense_shape != self.dense_shape:
            raise ValueError("shape mismatch")
        return CsrTensor(
            np.concatenate([self.indices, other.indices]),
            np.concatenate([self.values, other.values]),
            self.dense_shape)

    def coalesce(self) -> "CsrTensor":
        """Merge duplicate rows (sum), sort by row id."""
        uniq, inv = np.unique(self.indices, return_inverse=True)
        vals = np.zeros((len(uniq),) + self.values.shape[1:],
                        self.values.dtype)
        np.add.at(vals, inv, self.values)
        return CsrTensor(uniq, vals, self.dense_shape)
