"""Model-parallel checkpoint resharding (merge/split across MP degrees).

Reference: ``deepspeed/runtime/state_dict_factory.py:199`` — the Megatron
loader that retargets a checkpoint saved at MP degree N onto degree M by
concatenating or slicing each tensor along its parallel dimension, with the
QKV projection handled specially (each rank's shard interleaves its q, k, v
slices, so a naive concat scrambles heads; the reference splits into thirds
per rank before merging — ``megatron_sd_loader`` qkv handling).

TPU-native framing: rules are the same (regex → action) declarative shape
as ``models/partition.py``; actions are ``("cat", axis)``, ``("qkv", axis)``
or ``None`` (replicated — shards must agree, take the first). The in-tree
GPT family's rules are provided; any Megatron-layout external checkpoint
can supply its own.
"""

import re
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np


def gpt_mp_rules() -> Tuple[Tuple[str, Optional[Tuple[str, int]]], ...]:
    """MP merge/split rules for the in-tree GPT family — mirrors
    ``gpt_partition_rules`` (column-parallel qkv/fc-in on the output dim,
    row-parallel proj/fc-out on the input dim, vocab-parallel embedding)."""
    return (
        (r".*c_attn/kernel$", ("qkv", 1)),
        (r".*c_attn/bias$", ("qkv", 0)),
        (r".*c_fc/kernel$", ("cat", 1)),
        (r".*c_fc/bias$", ("cat", 0)),
        (r".*(c_proj|mlp_proj)/kernel$", ("cat", 0)),
        (r".*(c_proj|mlp_proj)/bias$", None),
        (r".*wte$", ("cat", 0)),
        (r".*lm_head/kernel$", ("cat", 1)),
        (r".*", None),
    )


def _path_str(path) -> str:
    return "/".join(str(getattr(p, "key", getattr(p, "idx", p)))
                    for p in path)


def _action_for(name: str, rules) -> Optional[Tuple[str, int]]:
    for pat, action in rules:
        if re.search(pat, name):
            return action
    return None


def _merge_qkv(shards: Sequence[np.ndarray], axis: int) -> np.ndarray:
    """Each shard holds [.., 3*d_r] with its q|k|v thirds interleaved; a
    correct merge concatenates all q thirds, then k, then v."""
    thirds = [np.split(s, 3, axis=axis) for s in shards]
    return np.concatenate(
        [np.concatenate([t[i] for t in thirds], axis=axis)
         for i in range(3)], axis=axis)


def _split_qkv(full: np.ndarray, mp: int, axis: int) -> List[np.ndarray]:
    q, k, v = np.split(full, 3, axis=axis)
    qs = np.split(q, mp, axis=axis)
    ks = np.split(k, mp, axis=axis)
    vs = np.split(v, mp, axis=axis)
    return [np.concatenate([qs[r], ks[r], vs[r]], axis=axis)
            for r in range(mp)]


def merge_mp_checkpoints(shards: Sequence[Any],
                         rules=None) -> Any:
    """Merge per-MP-rank param trees (list ordered by rank) into the full
    tree (reference ``merge_state_dict``, state_dict_factory.py:199)."""
    rules = rules if rules is not None else gpt_mp_rules()
    if len(shards) == 1:
        return shards[0]

    flat0, treedef = jax.tree_util.tree_flatten_with_path(shards[0])
    flat_rest = [jax.tree_util.tree_flatten_with_path(s)[0]
                 for s in shards[1:]]

    out = []
    for i, (path, leaf0) in enumerate(flat0):
        name = _path_str(path)
        pieces = [np.asarray(leaf0)] + [np.asarray(f[i][1])
                                        for f in flat_rest]
        action = _action_for(name, rules)
        if action is None:
            for p in pieces[1:]:
                if p.shape != pieces[0].shape:
                    raise ValueError(
                        f"replicated leaf '{name}' differs across MP shards")
            out.append(pieces[0])
        elif action[0] == "cat":
            out.append(np.concatenate(pieces, axis=action[1]))
        elif action[0] == "qkv":
            out.append(_merge_qkv(pieces, action[1]))
        else:
            raise ValueError(f"unknown MP action {action} for '{name}'")
    return jax.tree_util.tree_unflatten(treedef, out)


def split_mp_checkpoint(tree: Any, mp: int, rules=None) -> List[Any]:
    """Split a full tree into ``mp`` per-rank trees (reference
    ``split_state_dict``, the 1→N direction of MP retargeting)."""
    rules = rules if rules is not None else gpt_mp_rules()
    if mp == 1:
        return [tree]
    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    per_rank: List[List[np.ndarray]] = [[] for _ in range(mp)]
    for path, leaf in flat:
        name = _path_str(path)
        leaf = np.asarray(leaf)
        action = _action_for(name, rules)
        if action is None:
            for r in range(mp):
                per_rank[r].append(leaf)
            continue
        kind, axis = action
        if leaf.shape[axis] % (3 * mp if kind == "qkv" else mp):
            raise ValueError(
                f"'{name}' dim {axis} ({leaf.shape[axis]}) not divisible "
                f"for mp={mp}")
        pieces = (_split_qkv(leaf, mp, axis) if kind == "qkv"
                  else np.split(leaf, mp, axis=axis))
        for r in range(mp):
            per_rank[r].append(pieces[r])
    return [jax.tree_util.tree_unflatten(treedef, leaves)
            for leaves in per_rank]


def reshard_mp_checkpoint(shards: Sequence[Any], target_mp: int,
                          rules=None) -> List[Any]:
    """N→M retargeting: merge then re-split (reference ``check_ckpt_list``
    + merge/split dispatch)."""
    full = merge_mp_checkpoints(shards, rules)
    return split_mp_checkpoint(full, target_mp, rules)
