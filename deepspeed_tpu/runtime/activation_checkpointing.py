"""Activation checkpointing subsystem.

Reference: ``deepspeed/runtime/activation_checkpointing/checkpointing.py``
(``configure``/``is_configured``/``checkpoint`` + partition/cpu-offload
options). The torch version re-runs forward under ``torch.autograd`` with
hand-partitioned saved tensors; on TPU every option maps onto
``jax.checkpoint`` policies, which XLA folds into the backward pass:

- default                       → ``dots_with_no_batch_dims_saveable``
  (save matmul outputs, recompute elementwise — the standard sweet spot);
- ``partition_activations``     → ``nothing_saveable`` (recompute
  everything; saved residuals are already GSPMD-sharded over the mesh, so
  "partitioning" saved activations is the sharding, and this flag chooses
  max recompute);
- ``cpu_checkpointing``         → ``offload_dot_with_no_batch_dims``
  (saved matmul activations live in host memory — ZeRO-R's cpu
  checkpointing);
- ``number_checkpoints``        → recorded for model families that chunk
  their block scan (`every_n` remat granularity).

Model families consume ``remat_policy()`` through their ``remat`` flag; the
engine calls ``configure`` from the config block so user code using the
reference-style module API (``deepspeed_tpu.checkpointing.checkpoint``)
works unchanged.
"""

from typing import Any, Callable, Optional

import jax

_config = None


def configure(mpu_=None, deepspeed_config=None, partition_activations=None,
              contiguous_checkpointing=None, num_checkpoints=None,
              checkpoint_in_cpu=None, synchronize=None, profile=None):
    """Set the module-level policy (reference checkpointing.py:configure).

    Accepts either a parsed ``DeepSpeedTPUConfig`` (uses its
    activation_checkpointing block) or the individual keyword flags.
    """
    global _config
    from deepspeed_tpu.config.config import ActivationCheckpointingConfig

    if deepspeed_config is not None and hasattr(deepspeed_config,
                                                "activation_checkpointing"):
        _config = deepspeed_config.activation_checkpointing
    else:
        _config = ActivationCheckpointingConfig(
            partition_activations=bool(partition_activations or False),
            contiguous_memory_optimization=bool(
                contiguous_checkpointing or False),
            number_checkpoints=num_checkpoints,
            synchronize_checkpoint_boundary=bool(synchronize or False),
            profile=bool(profile or False),
            cpu_checkpointing=bool(checkpoint_in_cpu or False),
        )
    return _config


def is_configured() -> bool:
    return _config is not None


def get_config():
    return _config


def reset():
    global _config
    _config = None


def remat_policy(cfg=None) -> Optional[Callable]:
    """The jax.checkpoint policy the active config maps to."""
    cfg = cfg if cfg is not None else _config
    p = jax.checkpoint_policies
    if cfg is None:
        return p.dots_with_no_batch_dims_saveable
    if cfg.cpu_checkpointing:
        return p.offload_dot_with_no_batch_dims("device", "pinned_host")
    if cfg.partition_activations:
        return p.nothing_saveable
    return p.dots_with_no_batch_dims_saveable


def checkpoint(function: Callable, *args, **kwargs) -> Any:
    """Reference-API rematerialized call: runs ``function(*args)`` now,
    recomputing activations in the backward per the configured policy."""
    fn = jax.checkpoint(function, policy=remat_policy())
    return fn(*args, **kwargs)
