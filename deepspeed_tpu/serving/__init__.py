"""Serving tier — continuous batching, paged KV cache, SLO telemetry.

The production inference path (docs/SERVING.md): a step-driven
:class:`ServeEngine` doing Orca/vLLM-style in-flight batching over the
existing :class:`~deepspeed_tpu.inference.engine.InferenceEngine`, with a
paged blockwise KV cache (optionally int8 via the shared
``comm/quantize.py`` RTNE core) and serving SLO metrics through the
telemetry stack. ``deepspeed_tpu.init_serving(...)`` is the one-call
entry point.
"""

from deepspeed_tpu.serving.engine import SERVING_METRIC_TAGS, ServeEngine
from deepspeed_tpu.serving.kv_cache import (BlockPool, PagedLayerCache,
                                            init_paged_pools, pack_prefill)
from deepspeed_tpu.serving.resilience import (TERMINAL_STATUSES,
                                              ResilienceManager)
from deepspeed_tpu.serving.scheduler import (PrefixCache, Request,
                                             Scheduler, Sequence)

__all__ = [
    "BlockPool", "PagedLayerCache", "PrefixCache", "Request",
    "ResilienceManager", "SERVING_METRIC_TAGS", "ServeEngine",
    "Scheduler", "Sequence", "TERMINAL_STATUSES", "init_paged_pools",
    "pack_prefill",
]
