"""Paged/blockwise KV cache — the serving tier's memory system.

vLLM's PagedAttention insight (arXiv 2309.06180) re-done TPU-native: the
KV cache is a **preallocated pool of fixed-size blocks** plus per-sequence
**block tables**, so sequences of wildly different lengths share one HBM
allocation with no fragmentation and no reallocation as they grow. Every
device op here is **static-shape** — pool, block table and gather sizes
are fixed at engine build — so XLA compiles the decode program once and
never retraces as sequences grow, join or leave (the per-request
``dynamic_update_slice`` cache of ``inference/engine.py`` recompiles per
(batch, length) pair; this is what replaces it under continuous batching).

Layout (per transformer layer, all layers share one block table):

- ``k``/``v`` pool: ``[num_blocks, block_size, heads, head_dim]`` in the
  model's compute dtype — or **int8** with per-(token, head) fp32 scales
  ``[num_blocks, block_size, heads]`` when ``int8=True``. Quantization is
  the SAME deterministic RTNE blockwise round-trip the DCN gradient path
  uses (:func:`deepspeed_tpu.comm.quantize.quantize_blockwise` with
  ``block_size=head_dim``) — one int8 implementation in the tree.
- block table: ``[batch_slots, max_blocks_per_seq]`` int32, row ``b``
  listing the pool blocks of the sequence in slot ``b``. **Block 0 is a
  reserved scratch block**: inactive slots point at it, so their (masked,
  discarded) decode writes land somewhere harmless and the program needs
  no branch on slot liveness.

Host-side block accounting (:class:`BlockPool`) is plain python — a free
list is microseconds per step and never touches the device.
"""

from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from deepspeed_tpu.comm.quantize import quantize_blockwise


class BlockPool:
    """Host-side free-list allocator over ``num_blocks`` pool slots.

    Block 0 is reserved as the scratch block for inactive batch slots and
    is never handed out; ``capacity`` is therefore ``num_blocks - 1``.

    Blocks are **ref-counted** so the prefix cache can share immutable
    prompt-head blocks copy-on-write across sequences
    (``serving/scheduler.py PrefixCache``): ``alloc`` hands out blocks at
    refcount 1, ``share`` bumps an already-allocated block, and
    ``release`` decrements — a block returns to the free list only when
    its last holder lets go. A pool with no sharing behaves exactly like
    the plain free list it used to be.
    """

    SCRATCH = 0

    def __init__(self, num_blocks: int):
        if num_blocks < 2:
            raise ValueError(f"need >= 2 blocks (1 is reserved scratch), "
                             f"got {num_blocks}")
        self.num_blocks = int(num_blocks)
        self._free: List[int] = list(range(1, self.num_blocks))
        # Mirror of _free for O(1) double-free checks: releasing a long
        # sequence must stay microseconds even at multi-thousand-block
        # pools.
        self._free_set = set(self._free)
        self._refs: Dict[int, int] = {}

    @property
    def capacity(self) -> int:
        return self.num_blocks - 1

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def used_blocks(self) -> int:
        return self.capacity - len(self._free)

    def alloc(self, n: int) -> Optional[List[int]]:
        """``n`` blocks or None (never a partial grant — the caller either
        admits a sequence whole or leaves it queued)."""
        if n < 0:
            raise ValueError(f"alloc({n})")
        if n > len(self._free):
            return None
        taken, self._free = self._free[:n], self._free[n:]
        self._free_set.difference_update(taken)
        for b in taken:
            self._refs[b] = 1
        return taken

    def share(self, blocks: List[int]) -> None:
        """Take one more reference on already-allocated blocks (the COW
        adoption path — a new sequence, or the prefix cache itself,
        becomes a co-holder of an immutable prompt-head block)."""
        for b in blocks:
            if b == self.SCRATCH:
                raise ValueError("scratch block cannot be shared")
            if b not in self._refs:
                raise ValueError(f"share of unallocated block {b}")
        for b in blocks:
            self._refs[b] += 1

    def refcount(self, block: int) -> int:
        return self._refs.get(block, 0)

    def release(self, blocks: List[int]) -> None:
        """Drop one reference per block; a block frees only at zero."""
        for b in blocks:
            if b == self.SCRATCH:
                raise ValueError("scratch block cannot be released")
            if b in self._free_set or b not in self._refs:
                raise ValueError(f"double free of block {b}")
        for b in blocks:
            self._refs[b] -= 1
            if self._refs[b] == 0:
                del self._refs[b]
                self._free.append(b)
                self._free_set.add(b)


def init_paged_pools(cfg, num_blocks: int, block_size: int,
                     int8: bool = False, dtype=None) -> Tuple:
    """Per-layer ``(k, v, k_scale, v_scale)`` pool arrays (scales are None
    in the fp path). Zero-initialised: scratch/unwritten slots dequantize
    to exact zeros, so masked attention terms stay exactly ``0 * 0``."""
    dtype = dtype if dtype is not None else cfg.dtype
    shape = (num_blocks, block_size, cfg.num_heads, cfg.head_dim)
    sshape = (num_blocks, block_size, cfg.num_heads)
    layers = []
    for _ in range(cfg.num_layers):
        if int8:
            layers.append((jnp.zeros(shape, jnp.int8),
                           jnp.zeros(shape, jnp.int8),
                           jnp.ones(sshape, jnp.float32),
                           jnp.ones(sshape, jnp.float32)))
        else:
            layers.append((jnp.zeros(shape, dtype),
                           jnp.zeros(shape, dtype), None, None))
    return tuple(layers)


def _quant_tokens(x: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """[..., H, D] float -> (int8 [..., H, D], fp32 scales [..., H]) —
    one RTNE quantization block per (token, head) vector."""
    q, s = quantize_blockwise(x.astype(jnp.float32), x.shape[-1])
    return q, s[..., 0]        # head_dim is one block: drop the block axis


@jax.tree_util.register_pytree_node_class
class PagedLayerCache:
    """One layer's view of the paged cache inside a jitted decode/prefill
    program: pools + the batch's block table and write positions.

    Passed as the per-layer cache to the GPT family's cache mode; the
    block calls :meth:`update` with this step's ``k``/``v`` chunk and gets
    back the updated cache, the full gathered K/V and the key-validity
    mask. All shapes are static: the gather is always
    ``[B, max_blocks * block_size, H, D]`` regardless of true lengths.
    """

    def __init__(self, k: jax.Array, v: jax.Array,
                 k_scale: Optional[jax.Array], v_scale: Optional[jax.Array],
                 block_table: jax.Array, pos: jax.Array,
                 block_size: int, dtype_name: str = "bfloat16",
                 attn_impl: str = "gather", clamp_writes: bool = False):
        self.k = k
        self.v = v
        self.k_scale = k_scale
        self.v_scale = v_scale
        self.block_table = block_table      # [B, MB] int32
        self.pos = pos                      # [B] int32 — next write index
        self.block_size = int(block_size)
        self.dtype_name = dtype_name
        # Static (aux) knobs of the serving fast path (docs/SERVING.md):
        # ``attn_impl`` — "gather" (the materializing fallback, and the
        # bit-identical-to-PR-8 default) or "kernel" (the Pallas paged
        # decode-attention kernel; the model's paged branch reads it).
        # ``clamp_writes`` — route out-of-window writes to the scratch
        # block instead of relying on in-bounds positions; the
        # speculative-decode verify chunk can legally overshoot a
        # sequence's allocated blocks (rejected-token lookahead) and its
        # garbage must land somewhere harmless. Off by default: the plain
        # decode path never overshoots and must not pay the extra ops.
        self.attn_impl = str(attn_impl)
        self.clamp_writes = bool(clamp_writes)

    # -- pytree ---------------------------------------------------------
    def tree_flatten(self):
        return ((self.k, self.v, self.k_scale, self.v_scale,
                 self.block_table, self.pos),
                (self.block_size, self.dtype_name, self.attn_impl,
                 self.clamp_writes))

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children, block_size=aux[0], dtype_name=aux[1],
                   attn_impl=aux[2], clamp_writes=aux[3])

    # -- properties -----------------------------------------------------
    @property
    def int8(self) -> bool:
        return self.k_scale is not None

    @property
    def key_len(self) -> int:
        """Static gathered key-axis length (max_blocks * block_size)."""
        return self.block_table.shape[1] * self.block_size

    @property
    def pools(self) -> Tuple:
        return (self.k, self.v, self.k_scale, self.v_scale)

    # -- traced ops -----------------------------------------------------
    def _write(self, pool, scale, chunk):
        """Scatter ``chunk`` [B, S, H, D] at per-row positions
        ``pos..pos+S-1`` through the block table."""
        b, s = chunk.shape[:2]
        idx = self.pos[:, None] + jnp.arange(s)[None, :]        # [B, S]
        rows = jnp.arange(b)[:, None]
        if self.clamp_writes:
            # Out-of-window positions (speculative lookahead past a
            # sequence's last real write) land in the scratch block —
            # never in a real block another row (or this one) owns.
            mb = self.block_table.shape[1]
            blk = self.block_table[rows,
                                   jnp.minimum(idx // self.block_size,
                                               mb - 1)]
            blk = jnp.where(idx < mb * self.block_size, blk, 0)
        else:
            blk = self.block_table[rows, idx // self.block_size]  # [B, S]
        off = idx % self.block_size
        if scale is not None:
            q, sc = _quant_tokens(chunk)
            return pool.at[blk, off].set(q), scale.at[blk, off].set(sc)
        return pool.at[blk, off].set(chunk.astype(pool.dtype)), None

    def _gather(self, pool, scale):
        """[B, MB, BS, H, D] pool gather -> [B, L, H, D] keys/values."""
        b, mb = self.block_table.shape
        g = pool[self.block_table]                # [B, MB, BS, H, D]
        g = g.reshape(b, self.key_len, *pool.shape[2:])
        if scale is not None:
            # Per-(token, head) dequant — the inverse of _quant_tokens'
            # head_dim-block RTNE (comm/quantize.py round-trip semantics).
            sc = scale[self.block_table].reshape(b, self.key_len,
                                                 scale.shape[-1])
            g = g.astype(jnp.float32) * sc[..., None]
        return g.astype(jnp.dtype(self.dtype_name))

    def update(self, k_new: jax.Array, v_new: jax.Array):
        """Write this step's ``[B, S, H, D]`` chunk, gather the full cache.

        Returns ``(new_cache, K [B, L, H, D], V, mask [B, 1, S, L])`` where
        the mask makes key ``j`` visible to query ``i`` iff
        ``j <= pos + i`` — the cached past plus this chunk's causal prefix
        (scratch and not-yet-written slots are always masked out).
        """
        b, s = k_new.shape[:2]
        k, ks = self._write(self.k, self.k_scale, k_new)
        v, vs = self._write(self.v, self.v_scale, v_new)
        new = PagedLayerCache(k, v, ks, vs, self.block_table, self.pos,
                              self.block_size, self.dtype_name,
                              self.attn_impl, self.clamp_writes)
        kk = new._gather(k, ks)
        vv = new._gather(v, vs)
        qpos = self.pos[:, None] + jnp.arange(s)[None, :]        # [B, S]
        kpos = jnp.arange(self.key_len)
        mask = kpos[None, None, :] <= qpos[:, :, None]           # [B, S, L]
        return new, kk, vv, mask[:, None]                        # [B,1,S,L]

    def update_attend(self, q: jax.Array, k_new: jax.Array,
                      v_new: jax.Array,
                      softmax_scale: Optional[float] = None):
        """Fast-path form of :meth:`update`: write the chunk, then run
        the Pallas paged decode-attention kernel straight over the pools
        through the block table — the gathered ``[B, L, H, D]`` K/V copy
        (and, for int8 pools, its dequantized fp form) is never
        materialized. Returns ``(new_cache, o [B, S, H, D])``; visibility
        semantics are identical to the gather path (``kpos <= pos + i``,
        tier-1 parity-tested in tests/test_serving_fastpath.py)."""
        from deepspeed_tpu.ops.transformer.paged_attention import \
            paged_decode_attention

        k, ks = self._write(self.k, self.k_scale, k_new)
        v, vs = self._write(self.v, self.v_scale, v_new)
        new = PagedLayerCache(k, v, ks, vs, self.block_table, self.pos,
                              self.block_size, self.dtype_name,
                              self.attn_impl, self.clamp_writes)
        o = paged_decode_attention(q, k, v, ks, vs, self.block_table,
                                   self.pos, block_size=self.block_size,
                                   softmax_scale=softmax_scale)
        return new, o.astype(q.dtype)


@jax.tree_util.register_pytree_node_class
class ChunkedLayerCache:
    """One layer's view of the paged cache inside the **mixed** (chunked
    prefill) program: the batch axis is a flat ragged token batch
    ``[T]`` — decode tokens plus prefill chunks — where token ``t``
    belongs to batch slot ``slots[t]`` and sits at cache position
    ``pos[t]`` of its sequence. Pad tokens carry the spare all-scratch
    table row, so their writes land in block 0 and their (discarded)
    attention reads stay masked.

    Used by the GPT family's paged branch exactly like
    :class:`PagedLayerCache` with ``attn_impl == "kernel"`` — the model
    hands a ``[1, T, H, D]`` chunk to :meth:`update_attend` and gets the
    attended output back; visibility is per ragged segment
    (``kpos <= pos[t]`` over the token's own block-table row), which is
    exactly the bucketed path's causal semantics, so the two paths are
    token-identical (tier-1 parity-tested in
    tests/test_chunked_prefill.py).
    """

    attn_impl = "chunked"       # static: routes the model's paged branch

    def __init__(self, k: jax.Array, v: jax.Array,
                 k_scale: Optional[jax.Array], v_scale: Optional[jax.Array],
                 block_table: jax.Array, slots: jax.Array, pos: jax.Array,
                 block_size: int, dtype_name: str = "bfloat16"):
        self.k = k
        self.v = v
        self.k_scale = k_scale
        self.v_scale = v_scale
        self.block_table = block_table      # [B + 1, MB] int32 (row B: pads)
        self.slots = slots                  # [T] int32 — token's batch slot
        self.pos = pos                      # [T] int32 — token's position
        self.block_size = int(block_size)
        self.dtype_name = dtype_name

    # -- pytree ---------------------------------------------------------
    def tree_flatten(self):
        return ((self.k, self.v, self.k_scale, self.v_scale,
                 self.block_table, self.slots, self.pos),
                (self.block_size, self.dtype_name))

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children, block_size=aux[0], dtype_name=aux[1])

    @property
    def int8(self) -> bool:
        return self.k_scale is not None

    @property
    def pools(self) -> Tuple:
        return (self.k, self.v, self.k_scale, self.v_scale)

    # -- traced ops -----------------------------------------------------
    def _write(self, pool, scale, chunk):
        """Scatter ``chunk`` [T, H, D] — one write per ragged token at
        its own ``(slot, pos)``. Pad tokens all collide on the scratch
        block; real tokens never do (positions within a sequence are
        distinct and tables are disjoint)."""
        blk = self.block_table[self.slots, self.pos // self.block_size]
        off = self.pos % self.block_size                         # [T]
        if scale is not None:
            q, sc = _quant_tokens(chunk)
            return pool.at[blk, off].set(q), scale.at[blk, off].set(sc)
        return pool.at[blk, off].set(chunk.astype(pool.dtype)), None

    def update_attend(self, q: jax.Array, k_new: jax.Array,
                      v_new: jax.Array,
                      softmax_scale: Optional[float] = None):
        """Write the ragged batch's K/V, then run the chunked-prefill
        kernel straight over the pools through per-token block tables.
        ``q``/``k_new``/``v_new``: [1, T, H, D] (the model's flat batch
        rides as one row). Returns ``(new_cache, o [1, T, H, D])``."""
        from deepspeed_tpu.ops.transformer.chunked_prefill import \
            chunked_prefill_attention

        k, ks = self._write(self.k, self.k_scale, k_new[0])
        v, vs = self._write(self.v, self.v_scale, v_new[0])
        new = ChunkedLayerCache(k, v, ks, vs, self.block_table, self.slots,
                                self.pos, self.block_size, self.dtype_name)
        table = self.block_table[self.slots]                     # [T, MB]
        o = chunked_prefill_attention(q[0], k, v, ks, vs, table, self.pos,
                                      block_size=self.block_size,
                                      softmax_scale=softmax_scale)
        return new, o[None].astype(q.dtype)


def pack_prefill(pools: Tuple, blocks: jax.Array,
                 k_stack: jax.Array, v_stack: jax.Array) -> Tuple:
    """Scatter a prefilled contiguous cache into pool blocks (jit this).

    ``pools``: the per-layer ``(k, v, k_scale, v_scale)`` tuple;
    ``blocks``: [nb] int32 pool blocks assigned to the sequence;
    ``k_stack``/``v_stack``: [layers, T, H, D] from the prefill forward,
    with ``T == nb * block_size`` (bucketed — trailing positions beyond
    the true prompt length carry garbage that stays masked by ``pos``).
    """
    nb = blocks.shape[0]
    out = []
    for i, (k, v, ks, vs) in enumerate(pools):
        bs = k.shape[1]
        kb = k_stack[i].reshape(nb, bs, *k.shape[2:])
        vb = v_stack[i].reshape(nb, bs, *v.shape[2:])
        if ks is not None:
            kq, ksc = _quant_tokens(kb)
            vq, vsc = _quant_tokens(vb)
            out.append((k.at[blocks].set(kq), v.at[blocks].set(vq),
                        ks.at[blocks].set(ksc), vs.at[blocks].set(vsc)))
        else:
            out.append((k.at[blocks].set(kb.astype(k.dtype)),
                        v.at[blocks].set(vb.astype(v.dtype)), None, None))
    return tuple(out)
