"""Paged/blockwise KV cache — the serving tier's memory system.

vLLM's PagedAttention insight (arXiv 2309.06180) re-done TPU-native: the
KV cache is a **preallocated pool of fixed-size blocks** plus per-sequence
**block tables**, so sequences of wildly different lengths share one HBM
allocation with no fragmentation and no reallocation as they grow. Every
device op here is **static-shape** — pool, block table and gather sizes
are fixed at engine build — so XLA compiles the decode program once and
never retraces as sequences grow, join or leave (the per-request
``dynamic_update_slice`` cache of ``inference/engine.py`` recompiles per
(batch, length) pair; this is what replaces it under continuous batching).

Layout (per transformer layer, all layers share one block table):

- ``k``/``v`` pool: ``[num_blocks, block_size, heads, head_dim]`` in the
  model's compute dtype — or **int8** with per-(token, head) fp32 scales
  ``[num_blocks, block_size, heads]`` when ``int8=True``. Quantization is
  the SAME deterministic RTNE blockwise round-trip the DCN gradient path
  uses (:func:`deepspeed_tpu.comm.quantize.quantize_blockwise` with
  ``block_size=head_dim``) — one int8 implementation in the tree.
- block table: ``[batch_slots, max_blocks_per_seq]`` int32, row ``b``
  listing the pool blocks of the sequence in slot ``b``. **Block 0 is a
  reserved scratch block**: inactive slots point at it, so their (masked,
  discarded) decode writes land somewhere harmless and the program needs
  no branch on slot liveness.

Host-side block accounting (:class:`BlockPool`) is plain python — a free
list is microseconds per step and never touches the device.
"""

from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp

from deepspeed_tpu.comm.quantize import quantize_blockwise


class BlockPool:
    """Host-side free-list allocator over ``num_blocks`` pool slots.

    Block 0 is reserved as the scratch block for inactive batch slots and
    is never handed out; ``capacity`` is therefore ``num_blocks - 1``.
    """

    SCRATCH = 0

    def __init__(self, num_blocks: int):
        if num_blocks < 2:
            raise ValueError(f"need >= 2 blocks (1 is reserved scratch), "
                             f"got {num_blocks}")
        self.num_blocks = int(num_blocks)
        self._free: List[int] = list(range(1, self.num_blocks))
        # Mirror of _free for O(1) double-free checks: releasing a long
        # sequence must stay microseconds even at multi-thousand-block
        # pools.
        self._free_set = set(self._free)

    @property
    def capacity(self) -> int:
        return self.num_blocks - 1

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def used_blocks(self) -> int:
        return self.capacity - len(self._free)

    def alloc(self, n: int) -> Optional[List[int]]:
        """``n`` blocks or None (never a partial grant — the caller either
        admits a sequence whole or leaves it queued)."""
        if n < 0:
            raise ValueError(f"alloc({n})")
        if n > len(self._free):
            return None
        taken, self._free = self._free[:n], self._free[n:]
        self._free_set.difference_update(taken)
        return taken

    def release(self, blocks: List[int]) -> None:
        for b in blocks:
            if b == self.SCRATCH:
                raise ValueError("scratch block cannot be released")
            if b in self._free_set:
                raise ValueError(f"double free of block {b}")
        self._free.extend(blocks)
        self._free_set.update(blocks)


def init_paged_pools(cfg, num_blocks: int, block_size: int,
                     int8: bool = False, dtype=None) -> Tuple:
    """Per-layer ``(k, v, k_scale, v_scale)`` pool arrays (scales are None
    in the fp path). Zero-initialised: scratch/unwritten slots dequantize
    to exact zeros, so masked attention terms stay exactly ``0 * 0``."""
    dtype = dtype if dtype is not None else cfg.dtype
    shape = (num_blocks, block_size, cfg.num_heads, cfg.head_dim)
    sshape = (num_blocks, block_size, cfg.num_heads)
    layers = []
    for _ in range(cfg.num_layers):
        if int8:
            layers.append((jnp.zeros(shape, jnp.int8),
                           jnp.zeros(shape, jnp.int8),
                           jnp.ones(sshape, jnp.float32),
                           jnp.ones(sshape, jnp.float32)))
        else:
            layers.append((jnp.zeros(shape, dtype),
                           jnp.zeros(shape, dtype), None, None))
    return tuple(layers)


def _quant_tokens(x: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """[..., H, D] float -> (int8 [..., H, D], fp32 scales [..., H]) —
    one RTNE quantization block per (token, head) vector."""
    q, s = quantize_blockwise(x.astype(jnp.float32), x.shape[-1])
    return q, s[..., 0]        # head_dim is one block: drop the block axis


@jax.tree_util.register_pytree_node_class
class PagedLayerCache:
    """One layer's view of the paged cache inside a jitted decode/prefill
    program: pools + the batch's block table and write positions.

    Passed as the per-layer cache to the GPT family's cache mode; the
    block calls :meth:`update` with this step's ``k``/``v`` chunk and gets
    back the updated cache, the full gathered K/V and the key-validity
    mask. All shapes are static: the gather is always
    ``[B, max_blocks * block_size, H, D]`` regardless of true lengths.
    """

    def __init__(self, k: jax.Array, v: jax.Array,
                 k_scale: Optional[jax.Array], v_scale: Optional[jax.Array],
                 block_table: jax.Array, pos: jax.Array,
                 block_size: int, dtype_name: str = "bfloat16"):
        self.k = k
        self.v = v
        self.k_scale = k_scale
        self.v_scale = v_scale
        self.block_table = block_table      # [B, MB] int32
        self.pos = pos                      # [B] int32 — next write index
        self.block_size = int(block_size)
        self.dtype_name = dtype_name

    # -- pytree ---------------------------------------------------------
    def tree_flatten(self):
        return ((self.k, self.v, self.k_scale, self.v_scale,
                 self.block_table, self.pos),
                (self.block_size, self.dtype_name))

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children, block_size=aux[0], dtype_name=aux[1])

    # -- properties -----------------------------------------------------
    @property
    def int8(self) -> bool:
        return self.k_scale is not None

    @property
    def key_len(self) -> int:
        """Static gathered key-axis length (max_blocks * block_size)."""
        return self.block_table.shape[1] * self.block_size

    @property
    def pools(self) -> Tuple:
        return (self.k, self.v, self.k_scale, self.v_scale)

    # -- traced ops -----------------------------------------------------
    def _write(self, pool, scale, chunk):
        """Scatter ``chunk`` [B, S, H, D] at per-row positions
        ``pos..pos+S-1`` through the block table."""
        b, s = chunk.shape[:2]
        idx = self.pos[:, None] + jnp.arange(s)[None, :]        # [B, S]
        rows = jnp.arange(b)[:, None]
        blk = self.block_table[rows, idx // self.block_size]     # [B, S]
        off = idx % self.block_size
        if scale is not None:
            q, sc = _quant_tokens(chunk)
            return pool.at[blk, off].set(q), scale.at[blk, off].set(sc)
        return pool.at[blk, off].set(chunk.astype(pool.dtype)), None

    def _gather(self, pool, scale):
        """[B, MB, BS, H, D] pool gather -> [B, L, H, D] keys/values."""
        b, mb = self.block_table.shape
        g = pool[self.block_table]                # [B, MB, BS, H, D]
        g = g.reshape(b, self.key_len, *pool.shape[2:])
        if scale is not None:
            # Per-(token, head) dequant — the inverse of _quant_tokens'
            # head_dim-block RTNE (comm/quantize.py round-trip semantics).
            sc = scale[self.block_table].reshape(b, self.key_len,
                                                 scale.shape[-1])
            g = g.astype(jnp.float32) * sc[..., None]
        return g.astype(jnp.dtype(self.dtype_name))

    def update(self, k_new: jax.Array, v_new: jax.Array):
        """Write this step's ``[B, S, H, D]`` chunk, gather the full cache.

        Returns ``(new_cache, K [B, L, H, D], V, mask [B, 1, S, L])`` where
        the mask makes key ``j`` visible to query ``i`` iff
        ``j <= pos + i`` — the cached past plus this chunk's causal prefix
        (scratch and not-yet-written slots are always masked out).
        """
        b, s = k_new.shape[:2]
        k, ks = self._write(self.k, self.k_scale, k_new)
        v, vs = self._write(self.v, self.v_scale, v_new)
        new = PagedLayerCache(k, v, ks, vs, self.block_table, self.pos,
                              self.block_size, self.dtype_name)
        kk = new._gather(k, ks)
        vv = new._gather(v, vs)
        qpos = self.pos[:, None] + jnp.arange(s)[None, :]        # [B, S]
        kpos = jnp.arange(self.key_len)
        mask = kpos[None, None, :] <= qpos[:, :, None]           # [B, S, L]
        return new, kk, vv, mask[:, None]                        # [B,1,S,L]


def pack_prefill(pools: Tuple, blocks: jax.Array,
                 k_stack: jax.Array, v_stack: jax.Array) -> Tuple:
    """Scatter a prefilled contiguous cache into pool blocks (jit this).

    ``pools``: the per-layer ``(k, v, k_scale, v_scale)`` tuple;
    ``blocks``: [nb] int32 pool blocks assigned to the sequence;
    ``k_stack``/``v_stack``: [layers, T, H, D] from the prefill forward,
    with ``T == nb * block_size`` (bucketed — trailing positions beyond
    the true prompt length carry garbage that stays masked by ``pos``).
    """
    nb = blocks.shape[0]
    out = []
    for i, (k, v, ks, vs) in enumerate(pools):
        bs = k.shape[1]
        kb = k_stack[i].reshape(nb, bs, *k.shape[2:])
        vb = v_stack[i].reshape(nb, bs, *v.shape[2:])
        if ks is not None:
            kq, ksc = _quant_tokens(kb)
            vq, vsc = _quant_tokens(vb)
            out.append((k.at[blocks].set(kq), v.at[blocks].set(vq),
                        ks.at[blocks].set(ksc), vs.at[blocks].set(vsc)))
        else:
            out.append((k.at[blocks].set(kb.astype(k.dtype)),
                        v.at[blocks].set(vb.astype(v.dtype)), None, None))
    return tuple(out)
