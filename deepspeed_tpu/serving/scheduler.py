"""Continuous-batching scheduler — requests, sequences, admission, preemption.

Orca-style iteration-level scheduling (arXiv at OSDI'22; vLLM 2309.06180):
the decode batch is a fixed set of **slots** and scheduling decisions
happen only at decode-step boundaries — a finished sequence's slot and KV
blocks are handed to the next waiting request immediately (in-flight
batching), instead of draining the whole batch first (static batching).

Everything here is host-side python: the scheduler manipulates free
lists, deques and integers — microseconds per step, no device work. The
device-facing engine (`serving/engine.py`) asks it three questions per
step: who to prefill, who is active (and where their blocks are), and
who is finished.

Policies, deliberately boring and deterministic:

- **Admission**: FCFS. A request is admitted when a slot is free AND the
  block pool can cover its *whole prompt bucket* — never a partial grant,
  so a prefill can always complete.
- **Growth**: a decode write that crosses a block boundary needs one new
  block, taken from the pool at the step boundary *before* the write.
- **Preemption**: when growth finds the pool empty, the **youngest**
  running sequence is evicted — all its blocks released, its request
  requeued at the FRONT of the waiting queue (it restarts from the
  prompt; with greedy decoding the regenerated output is identical).
  Evicting the youngest minimises wasted work and cannot starve the
  oldest sequence, which therefore always completes. A
  previously-evicted request re-admits only when its WHOLE remaining
  run fits in free blocks — optimistic re-admission would thrash a full
  prefill away on every block the older sequence grows.
- **Prefix reuse** (``serving.prefix_cache``, docs/SERVING.md): a
  ref-counted trie over full prompt-head blocks keyed by their token
  content. A new request whose prompt head matches adopts the cached
  blocks copy-on-write (shared blocks are immutable — every write the
  engine ever issues lands at positions past the shared head) and only
  the unshared tail is prefilled, so a warm head's TTFT collapses to
  the tail. Cache-held blocks survive sequence completion AND
  youngest-first preemption (the cache holds its own pool reference);
  under pool pressure the cache evicts least-recently-used leaves
  before any running sequence is preempted.
"""

import collections
import itertools
import time
from dataclasses import dataclass, field
from typing import Deque, Dict, List, Optional, Tuple

from deepspeed_tpu.serving.kv_cache import BlockPool


@dataclass
class Request:
    """One generation request as submitted."""

    rid: int
    prompt: List[int]
    max_new_tokens: int
    eos_token_id: Optional[int] = None
    arrival: float = field(default_factory=time.monotonic)
    # Set once at the request's FIRST prefill and kept across preemption
    # restarts — TTFT is when the first token was ever produced, and each
    # request contributes exactly one serving/ttft_ms observation.
    first_token_time: Optional[float] = None
    # Times this request was evicted for KV pressure: a nonzero count
    # switches its re-admission to the pessimistic full-lifetime gate.
    preempted_count: int = 0
    # Set at the request's FIRST admission and kept across preemption
    # restarts — queue_wait is time until a slot was first granted, and
    # results[rid] carries it even with telemetry fully off.
    admitted_time: Optional[float] = None
    # Absolute monotonic deadline (serving.resilience, docs/SERVING.md
    # "Serving under failure"): past it the request is aborted at the
    # next step boundary with status deadline_expired. None = no limit.
    deadline: Optional[float] = None


@dataclass
class Sequence:
    """A running request: its slot, block table and progress."""

    request: Request
    slot: int
    bucket: int                       # prefill bucket (cache positions 0..)
    block_table: List[int] = field(default_factory=list)
    tokens: List[int] = field(default_factory=list)   # prompt + generated
    pos: int = 0                      # next cache write index
    admitted_step: int = 0
    # Prompt positions [0, shared_len) adopted from the prefix cache
    # (always a whole-block multiple; 0 = cold). The engine prefills only
    # the tail [shared_len, len(prompt)).
    shared_len: int = 0
    # Chunked-prefill cursor: prompt positions [0, prefilled) have their
    # KV written. The bucketed path prefills whole prompts at admission
    # and never reads this; the chunked engine advances it budget-bounded
    # chunks at a time until it reaches len(prompt) (docs/SERVING.md
    # "Chunked prefill admission").
    prefilled: int = 0

    @property
    def last_write_pos(self) -> int:
        """Highest cache position this sequence can ever write: the LAST
        sampled token's KV is never written (the run ends on it)."""
        return len(self.request.prompt) + self.request.max_new_tokens - 2

    @property
    def generated(self) -> int:
        return len(self.tokens) - len(self.request.prompt)

    def finished(self) -> bool:
        if self.generated >= self.request.max_new_tokens:
            return True
        eos = self.request.eos_token_id
        return (eos is not None and self.generated > 0
                and self.tokens[-1] == eos)


class _PrefixNode:
    """One cached prompt-head block: a trie edge keyed by the block's
    token content (exact tuple — "hashing" via dict keys, collision-free
    by construction)."""

    __slots__ = ("block", "children", "last_use", "parent", "key")

    def __init__(self, block: int, parent, key):
        self.block = block
        self.children: Dict[Tuple[int, ...], "_PrefixNode"] = {}
        self.last_use = 0
        self.parent = parent
        self.key = key


class PrefixCache:
    """Ref-counted prompt-head trie over KV pool blocks (docs/SERVING.md
    "Prefix-cache reuse").

    Nodes are **full** prompt blocks only — a partial tail block mixes
    prompt K/V with later decode writes and can never be shared — and a
    match is additionally capped one token short of the prompt, so the
    adopting sequence always has at least one tail token to prefill (the
    first-token logits must come from a real forward). Each node holds
    its own pool reference (``BlockPool.share``), which is what lets a
    warm head outlive the sequence that created it, including through
    youngest-first preemption. Shared blocks are immutable by
    construction: every engine write lands at positions at or past the
    adopter's ``shared_len``.
    """

    def __init__(self, pool: BlockPool, block_size: int):
        self.pool = pool
        self.block_size = int(block_size)
        self._root_children: Dict[Tuple[int, ...], _PrefixNode] = {}
        self.nodes = 0
        self.hits = 0                 # requests that adopted >= 1 block
        self.blocks_reused = 0        # running total of adopted blocks

    def _chunks(self, prompt: List[int], limit: int):
        bs = self.block_size
        for i in range(limit):
            yield i, tuple(prompt[i * bs:(i + 1) * bs])

    def match(self, prompt: List[int], step: int) -> List[int]:
        """Longest cached head as a block list, each block incref'd for
        the caller (who must ``pool.release`` them on any failure path).
        Capped at ``(len(prompt) - 1) // block_size`` blocks so a full
        hit still leaves a nonempty tail to prefill. The hit counters
        move only in :meth:`commit_hit` — a blocked head-of-queue
        request re-matches every step, and those failed admission
        attempts must not inflate the adoption evidence."""
        children = self._root_children
        blocks: List[int] = []
        for _i, chunk in self._chunks(prompt,
                                      (len(prompt) - 1) // self.block_size):
            node = children.get(chunk)
            if node is None:
                break
            node.last_use = step
            blocks.append(node.block)
            children = node.children
        if blocks:
            self.pool.share(blocks)
        return blocks

    def commit_hit(self, n_blocks: int) -> None:
        """Record one successful adoption (called by the scheduler after
        the matched request is actually admitted)."""
        if n_blocks:
            self.hits += 1
            self.blocks_reused += n_blocks

    def insert(self, prompt: List[int], block_table: List[int],
               step: int) -> None:
        """Register a prefilled sequence's full prompt blocks. Existing
        nodes are refreshed (LRU), new ones take a cache-owned pool
        reference on the sequence's block. First writer wins on a key
        collision — a racing duplicate prefill keeps its private block."""
        children = self._root_children
        parent = None
        for i, chunk in self._chunks(prompt,
                                     len(prompt) // self.block_size):
            node = children.get(chunk)
            if node is None:
                block = block_table[i]
                self.pool.share([block])
                node = _PrefixNode(block, parent, chunk)
                children[chunk] = node
                self.nodes += 1
            node.last_use = step
            parent = node
            children = node.children

    def _leaves(self) -> List[_PrefixNode]:
        out = []
        stack = list(self._root_children.values())
        while stack:
            n = stack.pop()
            if n.children:
                stack.extend(n.children.values())
            else:
                out.append(n)
        return out

    def _drop(self, node: _PrefixNode) -> None:
        owner = (node.parent.children if node.parent is not None
                 else self._root_children)
        del owner[node.key]
        self.nodes -= 1
        self.pool.release([node.block])

    def evict(self, need_free: int) -> int:
        """Free at least ``need_free`` pool blocks by dropping
        least-recently-used leaves (trie paths must stay contiguous from
        the root, so only leaves go). Only leaves whose block nobody
        else holds are dropped — a leaf co-held by a running sequence
        costs the pool nothing extra NOW (the block is alive either
        way), so dropping it would free no memory and only destroy the
        warm-restart path; it becomes evictable the moment its last
        co-holder releases. Returns blocks actually freed."""
        freed = 0
        while freed < need_free:
            sole = [n for n in self._leaves()
                    if self.pool.refcount(n.block) == 1]
            if not sole:
                break
            before = self.pool.free_blocks
            self._drop(min(sole, key=lambda n: n.last_use))
            freed += self.pool.free_blocks - before
        return freed

    def clear(self) -> None:
        """Drop every cached node (releases all cache-held refs) — the
        leak-check hook: with no sequences running, a cleared cache
        leaves the whole pool free."""
        while self.nodes:
            for node in self._leaves():
                self._drop(node)


class Scheduler:
    """Slot + block bookkeeping for one serving engine."""

    def __init__(self, num_slots: int, pool: BlockPool, block_size: int,
                 prefix_cache: Optional[PrefixCache] = None):
        self.num_slots = int(num_slots)
        self.pool = pool
        self.block_size = int(block_size)
        self.prefix_cache = prefix_cache
        self.waiting: Deque[Request] = collections.deque()
        self.running: Dict[int, Sequence] = {}            # slot -> seq
        self._free_slots: List[int] = list(range(self.num_slots))[::-1]
        # Admission-level batch cap (<= num_slots). The degradation
        # ladder (serving/resilience.py) shrinks it to shed batch
        # pressure WITHOUT recompiling the decode program — slots above
        # the cap simply stay empty, padding-masked like any idle slot.
        self.slot_cap = int(num_slots)
        self._ids = itertools.count()
        self.preempted_total = 0
        self.completed_total = 0
        # Request observatory back-reference (telemetry/requests.py) —
        # the engine sets it so admission/preemption mark the per-request
        # SLO ledger without relaying through the engine. None = off.
        self.accountant = None

    # -- submission -----------------------------------------------------
    def submit(self, prompt: List[int], max_new_tokens: int,
               eos_token_id: Optional[int] = None) -> int:
        rid = next(self._ids)
        self.waiting.append(Request(rid, list(prompt), int(max_new_tokens),
                                    eos_token_id))
        return rid

    def reserve_rid(self) -> int:
        """Draw the next request id WITHOUT enqueuing anything — a shed
        request (serving/resilience.py) still gets a real rid so its
        terminal record lands in ``results`` like every other request."""
        return next(self._ids)

    @property
    def queue_depth(self) -> int:
        return len(self.waiting)

    @property
    def active(self) -> List[Sequence]:
        return [self.running[s] for s in sorted(self.running)]

    def idle(self) -> bool:
        return not self.waiting and not self.running

    # -- admission ------------------------------------------------------
    def try_admit(self, bucket_of, step: int) -> Optional[Sequence]:
        """Admit the head-of-queue request if a slot is free and the pool
        covers its prompt bucket; returns the new Sequence (blocks
        allocated, not yet prefilled) or None."""
        if not self.waiting or not self._free_slots:
            return None
        if len(self.running) >= self.slot_cap:
            return None
        req = self.waiting[0]
        bucket = bucket_of(len(req.prompt))
        shared: List[int] = []
        if self.prefix_cache is not None:
            shared = self.prefix_cache.match(req.prompt, step)
        n_shared = len(shared)
        if req.preempted_count:
            # Already evicted once: the pool has proven too tight for
            # optimism. Re-admit only when its WHOLE remaining run fits
            # in free blocks (last sampled token writes no KV), else the
            # admit/prefill/evict cycle thrashes a full prefill away on
            # every block the older sequence grows. Adopted prefix
            # blocks need no free blocks — only the unshared remainder
            # counts.
            lifetime = max(bucket, len(req.prompt) + req.max_new_tokens - 1)
            need = -(-lifetime // self.block_size) - n_shared
            if self.pool.free_blocks < need:
                if shared:
                    self.pool.release(shared)
                return None
        tail_n = bucket // self.block_size - n_shared
        blocks = self.pool.alloc(tail_n)
        if blocks is None and self.prefix_cache is not None:
            # Cold cache entries yield to live admissions before any
            # running sequence would be preempted.
            self.prefix_cache.evict(tail_n - self.pool.free_blocks)
            blocks = self.pool.alloc(tail_n)
        if blocks is None:
            if shared:
                self.pool.release(shared)
            return None
        self.waiting.popleft()
        slot = self._free_slots.pop()
        if self.prefix_cache is not None:
            self.prefix_cache.commit_hit(n_shared)
        seq = Sequence(request=req, slot=slot, bucket=bucket,
                       block_table=shared + blocks, tokens=list(req.prompt),
                       pos=len(req.prompt), admitted_step=step,
                       shared_len=n_shared * self.block_size)
        self.running[slot] = seq
        if req.admitted_time is None:
            req.admitted_time = time.monotonic()
        if self.accountant is not None:
            self.accountant.on_admit(seq)
        return seq

    def register_prefix(self, seq: Sequence, step: int) -> None:
        """After a successful prefill: make this sequence's full prompt
        blocks adoptable by future requests (no-op without a cache)."""
        if self.prefix_cache is not None:
            self.prefix_cache.insert(seq.request.prompt, seq.block_table,
                                     step)

    # -- growth / preemption -------------------------------------------
    def ensure_capacity(self, seq: Sequence, lookahead: int = 0) -> bool:
        """Make sure ``seq`` can write its next token (``seq.pos``) plus
        ``lookahead`` further positions (speculative decoding's verify
        chunk writes ``pos..pos+k``), capped at the last position the
        sequence can ever write — chunk overshoot past that is routed to
        scratch and needs no blocks. Allocates a block when the write
        crosses into uncovered territory, evicting cold prefix-cache
        leaves first and then the YOUNGEST running sequence — possibly
        ``seq`` itself — when the pool is dry, so the oldest sequence
        always completes. Returns False when ``seq`` was the youngest
        and got evicted."""
        target = min(seq.pos + lookahead, seq.last_write_pos)
        while target >= len(seq.block_table) * self.block_size:
            got = self.pool.alloc(1)
            if got is None and self.prefix_cache is not None \
                    and self.prefix_cache.evict(1):
                got = self.pool.alloc(1)
            if got is not None:
                seq.block_table.extend(got)
                continue
            victim = self._youngest()
            if victim is seq and len(self.running) == 1:
                raise RuntimeError(
                    f"KV block pool exhausted: request {seq.request.rid} "
                    f"needs a block and there is no other sequence to "
                    f"preempt — the pool ({self.pool.capacity} blocks of "
                    f"{self.block_size}) cannot hold even one max-length "
                    f"sequence; raise serving.kv_num_blocks")
            self.preempt(victim)
            if victim is seq:
                return False
        return True

    def _youngest(self) -> Sequence:
        """Latest-admitted running sequence (ties broken by request id —
        the larger rid entered the queue later)."""
        return max(self.running.values(),
                   key=lambda s: (s.admitted_step, s.request.rid))

    def preempt(self, seq: Sequence) -> None:
        """Evict: release blocks + slot, requeue the ORIGINAL request at
        the front (it restarts from its prompt on re-admission)."""
        self._release(seq)
        seq.request.preempted_count += 1
        self.waiting.appendleft(seq.request)
        self.preempted_total += 1
        if self.accountant is not None:
            self.accountant.on_preempt(seq)

    # -- completion -----------------------------------------------------
    def finish(self, seq: Sequence) -> None:
        self._release(seq)
        self.completed_total += 1

    def abort(self, seq: Sequence) -> None:
        """Terminal eviction (deadline_expired / cancelled / teardown):
        release slot + blocks exactly once, DON'T requeue — the caller
        owns the terminal record."""
        self._release(seq)

    def _release(self, seq: Sequence) -> None:
        del self.running[seq.slot]
        self._free_slots.append(seq.slot)
        self.pool.release(seq.block_table)
        seq.block_table = []
