"""Continuous-batching scheduler — requests, sequences, admission, preemption.

Orca-style iteration-level scheduling (arXiv at OSDI'22; vLLM 2309.06180):
the decode batch is a fixed set of **slots** and scheduling decisions
happen only at decode-step boundaries — a finished sequence's slot and KV
blocks are handed to the next waiting request immediately (in-flight
batching), instead of draining the whole batch first (static batching).

Everything here is host-side python: the scheduler manipulates free
lists, deques and integers — microseconds per step, no device work. The
device-facing engine (`serving/engine.py`) asks it three questions per
step: who to prefill, who is active (and where their blocks are), and
who is finished.

Policies, deliberately boring and deterministic:

- **Admission**: FCFS. A request is admitted when a slot is free AND the
  block pool can cover its *whole prompt bucket* — never a partial grant,
  so a prefill can always complete.
- **Growth**: a decode write that crosses a block boundary needs one new
  block, taken from the pool at the step boundary *before* the write.
- **Preemption**: when growth finds the pool empty, the **youngest**
  running sequence is evicted — all its blocks released, its request
  requeued at the FRONT of the waiting queue (it restarts from the
  prompt; with greedy decoding the regenerated output is identical).
  Evicting the youngest minimises wasted work and cannot starve the
  oldest sequence, which therefore always completes. A
  previously-evicted request re-admits only when its WHOLE remaining
  run fits in free blocks — optimistic re-admission would thrash a full
  prefill away on every block the older sequence grows.
"""

import collections
import itertools
import time
from dataclasses import dataclass, field
from typing import Deque, Dict, List, Optional

from deepspeed_tpu.serving.kv_cache import BlockPool


@dataclass
class Request:
    """One generation request as submitted."""

    rid: int
    prompt: List[int]
    max_new_tokens: int
    eos_token_id: Optional[int] = None
    arrival: float = field(default_factory=time.monotonic)
    # Set once at the request's FIRST prefill and kept across preemption
    # restarts — TTFT is when the first token was ever produced, and each
    # request contributes exactly one serving/ttft_ms observation.
    first_token_time: Optional[float] = None
    # Times this request was evicted for KV pressure: a nonzero count
    # switches its re-admission to the pessimistic full-lifetime gate.
    preempted_count: int = 0


@dataclass
class Sequence:
    """A running request: its slot, block table and progress."""

    request: Request
    slot: int
    bucket: int                       # prefill bucket (cache positions 0..)
    block_table: List[int] = field(default_factory=list)
    tokens: List[int] = field(default_factory=list)   # prompt + generated
    pos: int = 0                      # next cache write index
    admitted_step: int = 0

    @property
    def generated(self) -> int:
        return len(self.tokens) - len(self.request.prompt)

    def finished(self) -> bool:
        if self.generated >= self.request.max_new_tokens:
            return True
        eos = self.request.eos_token_id
        return (eos is not None and self.generated > 0
                and self.tokens[-1] == eos)


class Scheduler:
    """Slot + block bookkeeping for one serving engine."""

    def __init__(self, num_slots: int, pool: BlockPool, block_size: int):
        self.num_slots = int(num_slots)
        self.pool = pool
        self.block_size = int(block_size)
        self.waiting: Deque[Request] = collections.deque()
        self.running: Dict[int, Sequence] = {}            # slot -> seq
        self._free_slots: List[int] = list(range(self.num_slots))[::-1]
        self._ids = itertools.count()
        self.preempted_total = 0
        self.completed_total = 0

    # -- submission -----------------------------------------------------
    def submit(self, prompt: List[int], max_new_tokens: int,
               eos_token_id: Optional[int] = None) -> int:
        rid = next(self._ids)
        self.waiting.append(Request(rid, list(prompt), int(max_new_tokens),
                                    eos_token_id))
        return rid

    @property
    def queue_depth(self) -> int:
        return len(self.waiting)

    @property
    def active(self) -> List[Sequence]:
        return [self.running[s] for s in sorted(self.running)]

    def idle(self) -> bool:
        return not self.waiting and not self.running

    # -- admission ------------------------------------------------------
    def try_admit(self, bucket_of, step: int) -> Optional[Sequence]:
        """Admit the head-of-queue request if a slot is free and the pool
        covers its prompt bucket; returns the new Sequence (blocks
        allocated, not yet prefilled) or None."""
        if not self.waiting or not self._free_slots:
            return None
        req = self.waiting[0]
        bucket = bucket_of(len(req.prompt))
        if req.preempted_count:
            # Already evicted once: the pool has proven too tight for
            # optimism. Re-admit only when its WHOLE remaining run fits
            # in free blocks (last sampled token writes no KV), else the
            # admit/prefill/evict cycle thrashes a full prefill away on
            # every block the older sequence grows.
            lifetime = max(bucket, len(req.prompt) + req.max_new_tokens - 1)
            if self.pool.free_blocks < -(-lifetime // self.block_size):
                return None
        blocks = self.pool.alloc(bucket // self.block_size)
        if blocks is None:
            return None
        self.waiting.popleft()
        slot = self._free_slots.pop()
        seq = Sequence(request=req, slot=slot, bucket=bucket,
                       block_table=blocks, tokens=list(req.prompt),
                       pos=len(req.prompt), admitted_step=step)
        self.running[slot] = seq
        return seq

    # -- growth / preemption -------------------------------------------
    def ensure_capacity(self, seq: Sequence) -> bool:
        """Make sure ``seq`` can write its next token (``seq.pos``).
        Allocates a block when the write crosses into uncovered territory,
        evicting the YOUNGEST running sequence — possibly ``seq`` itself —
        when the pool is dry, so the oldest sequence always completes.
        Returns False when ``seq`` was the youngest and got evicted."""
        while seq.pos >= len(seq.block_table) * self.block_size:
            got = self.pool.alloc(1)
            if got is not None:
                seq.block_table.extend(got)
                continue
            victim = self._youngest()
            if victim is seq and len(self.running) == 1:
                raise RuntimeError(
                    f"KV block pool exhausted: request {seq.request.rid} "
                    f"needs a block and there is no other sequence to "
                    f"preempt — the pool ({self.pool.capacity} blocks of "
                    f"{self.block_size}) cannot hold even one max-length "
                    f"sequence; raise serving.kv_num_blocks")
            self.preempt(victim)
            if victim is seq:
                return False
        return True

    def _youngest(self) -> Sequence:
        """Latest-admitted running sequence (ties broken by request id —
        the larger rid entered the queue later)."""
        return max(self.running.values(),
                   key=lambda s: (s.admitted_step, s.request.rid))

    def preempt(self, seq: Sequence) -> None:
        """Evict: release blocks + slot, requeue the ORIGINAL request at
        the front (it restarts from its prompt on re-admission)."""
        self._release(seq)
        seq.request.preempted_count += 1
        self.waiting.appendleft(seq.request)
        self.preempted_total += 1

    # -- completion -----------------------------------------------------
    def finish(self, seq: Sequence) -> None:
        self._release(seq)
        self.completed_total += 1

    def _release(self, seq: Sequence) -> None:
        del self.running[seq.slot]
        self._free_slots.append(seq.slot)
        self.pool.release(seq.block_table)
        seq.block_table = []
