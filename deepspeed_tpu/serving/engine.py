"""ServeEngine — continuous batching + SLO telemetry over the inference stack.

The production serving loop the ROADMAP's "millions of users" story needs,
layered on what the tree already has: the :class:`InferenceEngine` owns
params (TP sharding, int8 weights, dtype), ``serving/kv_cache.py`` owns KV
memory, ``serving/scheduler.py`` owns admission, and the telemetry stack
(registry/tracer/recompile detector) owns observability.

Execution model — **step-driven, three compiled programs, zero retraces
in steady state**:

- ``prefill`` (one program per power-of-two prompt **bucket**): a single
  sequence's prompt runs through the contiguous-cache forward, its first
  token is sampled in-program, and the per-layer K/V are scattered into
  the paged pool. Prefill and decode are **disaggregated**: a long prompt
  costs the decode batch at most ``max_prefills_per_step`` prefill
  dispatches per step boundary, never a retrace of the decode program.
- ``decode_step`` (ONE program, ever): the whole slot batch advances one
  token through the paged cache — fixed batch width, fixed block-table
  shape, per-row positions. Sequences join/leave by editing host-side
  numpy inputs, which XLA never sees as a new signature.
- scheduling between steps is pure host python (microseconds).

SLO telemetry rides the established contract: metrics through the
``MetricsRegistry`` (no sinks -> no-ops), spans through the ``StepTracer``
(disabled -> reusable null span, zero device syncs), and
``tools/serving_report.py`` renders TTFT/throughput/occupancy percentiles
from the same metrics JSONL the training loop writes.
"""

import functools
import time
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.inference.engine import (InferenceEngine, bucket_length,
                                            sample_logits)
from deepspeed_tpu.serving.kv_cache import (BlockPool, ChunkedLayerCache,
                                            PagedLayerCache,
                                            init_paged_pools, pack_prefill)
from deepspeed_tpu.serving.scheduler import (PrefixCache, Scheduler,
                                             Sequence)
from deepspeed_tpu.utils.logging import log_dist

# Every metric tag the serving engine can emit — pinned against
# docs/OBSERVABILITY.md in both directions by tests/test_doc_lint.py.
SERVING_METRIC_TAGS = frozenset({
    "serving/ttft_ms",
    "serving/tokens_per_sec",
    # Rolling-window decode throughput (window: telemetry.requests.
    # window_sec) — emitted only when the request accountant is on, so
    # the tag set with telemetry.requests off stays byte-identical.
    "serving/tokens_per_sec_window",
    "serving/batch_occupancy",
    "serving/kv_blocks_in_use",
    "serving/queue_depth",
    "serving/preempted_seqs",
    "serving/requests_completed",
    # decode fast path (docs/SERVING.md "Decode fast path"): per-piece
    # attribution so each win is separately measurable.
    "serving/decode_attn_kernel",
    "serving/prefix_hits",
    "serving/prefix_blocks_reused",
    "serving/spec_accept_rate",
    "serving/spec_tokens_per_verify",
    # Serving resilience (docs/SERVING.md "Serving under failure"):
    # emitted only when serving.resilience is on, so the off tag set
    # stays byte-identical.
    "serving/shed_requests",
    "serving/deadline_expired",
    "serving/cancelled",
    "serving/recoveries",
    "serving/retries",
    "serving/degraded_level",
    # Chunked prefill (docs/SERVING.md "Chunked prefill admission"):
    # emitted only when serving.chunked_prefill is on, so the off tag
    # set stays byte-identical.
    "serving/chunked_tokens_per_step",
    "serving/prefill_chunks_in_flight",
})


class ServeEngine:
    """Continuous-batching serving engine over an :class:`InferenceEngine`.

    ``engine``: an InferenceEngine wrapping a cache-capable causal LM (the
    in-tree GPT family). ``config``: a parsed ``ServingConfig`` (or None
    for defaults). ``telemetry``: the run's ``Telemetry`` facade — omit it
    (or pass a disabled one) and the engine performs zero telemetry
    work beyond host float arithmetic.

    Thread model: **none required** — ``submit()`` + ``step()`` are plain
    calls (tier-1 drives them directly); ``serve_forever()`` is a thin
    loop for a dedicated serving process.
    """

    def __init__(self, engine: InferenceEngine, config=None,
                 telemetry=None, capture_logits: bool = False,
                 measure_kv_quant_error: bool = False,
                 request_accountant=None, fault_plan=None):
        from deepspeed_tpu.config.config import ServingConfig
        from deepspeed_tpu.telemetry import null_telemetry

        if engine.model_cfg is None or not hasattr(engine.module, "cfg"):
            raise ValueError(
                "ServeEngine needs a cache-capable in-tree causal LM "
                f"(the GPT family); {type(engine.module).__name__} is not")
        self.engine = engine
        self.module = engine.module
        self.model_cfg = engine.model_cfg
        self.scfg = config if config is not None else ServingConfig()
        self.telemetry = telemetry if telemetry is not None \
            else null_telemetry()
        self.capture_logits = bool(capture_logits)

        model_max = int(getattr(self.model_cfg, "max_seq_len"))
        self.max_model_len = min(self.scfg.max_model_len or model_max,
                                 model_max)
        bs = self.scfg.kv_block_size
        self.block_size = bs
        self.max_blocks = -(-self.max_model_len // bs)   # ceil
        # Prompt buckets must be BS multiples (whole blocks) and their
        # positions must exist in the model (wpe rows) AND in the block
        # table width.
        self.bucket_cap = min(self.max_blocks * bs, (model_max // bs) * bs)
        if self.bucket_cap < bs:
            raise ValueError(
                f"serving.kv_block_size={bs} exceeds the usable context "
                f"({model_max}) — no prompt bucket fits")

        self.pool = BlockPool(self.scfg.kv_num_blocks)
        self.prefix_cache = (PrefixCache(self.pool, bs)
                             if self.scfg.prefix_cache else None)
        self.sched = Scheduler(self.scfg.max_batch_size, self.pool, bs,
                               prefix_cache=self.prefix_cache)
        self._dtype = engine.config.dtype
        self._dtype_name = jnp.dtype(self._dtype).name
        self._pools = init_paged_pools(
            self.model_cfg, self.scfg.kv_num_blocks, bs,
            int8=self.scfg.int8_kv_cache, dtype=self._dtype)

        self._prefill_jit: Dict[int, Any] = {}
        # -- decode fast path (docs/SERVING.md "Decode fast path") ------
        # "gather" (default) keeps the PR-8 program byte-for-byte: one
        # decode program over the FULL table window, no window slicing,
        # no kernel. "auto"/"kernel" turn on window capping (the decode
        # key axis covers only the max active length, ceiled to a
        # power-of-two block count — O(log max_blocks) compiled variants
        # instead of one) and, where the geometry tiles (or always,
        # under "kernel" — the Pallas interpreter covers CPU), the paged
        # decode-attention kernel.
        from deepspeed_tpu.ops.transformer.paged_attention import \
            paged_decode_ok
        mode = self.scfg.decode_attention
        self._fast_path = mode != "gather"
        if mode == "kernel":
            self._attn_impl = "kernel"
        elif mode == "auto":
            on_tpu = jax.devices()[0].platform == "tpu"
            self._attn_impl = (
                "kernel" if on_tpu and paged_decode_ok(
                    self.model_cfg.head_dim, bs) else "gather")
        else:
            self._attn_impl = "gather"
        self._decode_jits: Dict[Any, Any] = {}    # window bucket -> jit
        self._tail_prefill_jit: Dict[int, Any] = {}
        # -- speculative decoding ---------------------------------------
        self._spec_k = 0
        self._spec_jits: Dict[Any, Any] = {}
        if self.scfg.spec_decode:
            self._init_speculative()
        # -- chunked prefill (docs/SERVING.md "Chunked prefill
        # admission"): the third admission mode. Decode tokens and
        # prefill CHUNKS of admitted prompts share ONE ragged mixed
        # program (ops/transformer/chunked_prefill.py), bounded by a
        # per-step token budget — no per-bucket prefill compiles, no
        # head-of-line full-prompt stall, one compile ever. Off (the
        # default) keeps every hook a single attribute check and the
        # lowered bucketed programs + emitted tag set byte-identical.
        self._chunked = bool(self.scfg.chunked_prefill)
        self._chunk_budget = int(self.scfg.chunked_token_budget)
        self._mixed_jit = None
        self._chunk_tokens_last = 0
        if self._chunked:
            from deepspeed_tpu.ops.transformer.chunked_prefill import \
                chunked_prefill_ok
            on_tpu = jax.devices()[0].platform == "tpu"
            if on_tpu and not chunked_prefill_ok(self.model_cfg.head_dim,
                                                 bs):
                # The bucketed path stays the auto fallback (and the
                # parity oracle) on geometries the compiled kernel
                # cannot tile; off-TPU the Pallas interpreter takes any
                # shape.
                log_dist(
                    f"serving: chunked prefill requested but head_dim="
                    f"{self.model_cfg.head_dim}/block_size={bs} does not "
                    f"tile the kernel — falling back to bucketed "
                    f"admission", ranks=[0])
                self._chunked = False
            else:
                log_dist(
                    f"serving: chunked prefill on — token budget "
                    f"{self._chunk_budget}/step, one mixed program",
                    ranks=[0])
        # Request observatory (telemetry/requests.py): per-request SLO
        # ledger + engine serving-time partition. None (the default and
        # the telemetry.requests=off state) keeps every hook a single
        # attribute check and the emitted tag set byte-identical.
        self._req_acc = request_accountant
        if self._req_acc is not None:
            self._req_acc.spec_k = self._spec_k
            self.sched.accountant = self._req_acc
        # Serving resilience (serving/resilience.py; docs/SERVING.md
        # "Serving under failure"): deadlines + cancellation, SLO-aware
        # load shedding, in-flight recovery, degradation ladder. None
        # (the serving.resilience=off default) keeps every hook a single
        # attribute check and the lowered decode program + emitted tag
        # set byte-identical. Chaos (``fault_plan``) is independent: an
        # injected serve fault with resilience off crashes the loop —
        # the failure mode the manager exists to absorb.
        self._fault = fault_plan
        self._dispatch_attempts = 0      # decode dispatches, fault-keyed
        self._storm_template = None      # last submit args, for storms
        if self.scfg.resilience:
            from deepspeed_tpu.serving.resilience import ResilienceManager
            self._resil = ResilienceManager(self)
        else:
            self._resil = None
        # Numerics observatory surface (telemetry/numerics.py): with the
        # int8 KV cache AND the numerics opt-in on
        # (``telemetry.numerics.enabled`` — init_serving plumbs it;
        # telemetry-only deployments must not pay a per-prefill measure
        # inside the TTFT span), each prefill measures the RTNE
        # round-trip error of the K/V it just quantized into the pool
        # (one jitted measure per bucket, real positions only) — the
        # serving analogue of the DCN grad gauge.
        self._measure_kv = (bool(measure_kv_quant_error)
                            and bool(self.scfg.int8_kv_cache)
                            and self.telemetry.enabled)
        self._kv_err_jit: Dict[int, Any] = {}
        # Donate the pools: decode/pack rewrite them functionally, and
        # without donation XLA double-buffers the whole KV cache (2x HBM)
        # and copies it per token (same rationale as the training
        # engine's donated TrainState). Backends without donation (CPU
        # tier-1) just warn and copy.
        self._pack_jit = jax.jit(pack_prefill, donate_argnums=(0,))
        self._base_key = jax.random.PRNGKey(self.scfg.seed)
        self._step_count = 0
        # Cumulative decode work behind the throughput gauge: a
        # token-weighted rate (total tokens / total decode seconds) —
        # a mean over per-step instantaneous rates would overweight
        # fast steps and overstate throughput exactly when straggler
        # steps appear.
        self._decode_tokens = 0
        self._decode_sec = 0.0
        self.results: Dict[int, Dict[str, Any]] = {}
        # Host-side aggregates, kept regardless of telemetry (floats and
        # ints only — the SLO gauges are derived from these).
        # ``gathered_positions``: cumulative key positions the decode
        # program touched per row (window width x steps) — the modeled
        # HBM-traffic evidence behind the capped fallback
        # (tools/probe_serving_fastpath.py); ``full_positions`` is the
        # uncapped counterfactual.
        self.stats = {"decode_steps": 0, "occupancy_sum": 0.0,
                      "slot_assignments": {}, "kernel_steps": 0,
                      "gathered_positions": 0, "full_positions": 0,
                      "spec_rounds": 0, "spec_proposed": 0,
                      "spec_accepted": 0, "spec_new_tokens": 0}
        log_dist(
            f"serving: {self.scfg.max_batch_size} slots, KV pool "
            f"{self.pool.capacity}x{bs} positions "
            f"({'int8' if self.scfg.int8_kv_cache else self._dtype_name}), "
            f"max_model_len {self.max_model_len}", ranks=[0])

    # ------------------------------------------------------------------
    # submission / retrieval
    # ------------------------------------------------------------------
    def submit(self, prompt, max_new_tokens: int,
               eos_token_id: Optional[int] = None,
               deadline_ms: Optional[float] = None) -> int:
        """Queue one request; returns its request id. Never blocks —
        admission happens at the next ``step()`` boundary.

        ``deadline_ms`` (requires ``serving.resilience``): wall-clock
        budget from submission; past it the request is aborted at the
        next step boundary with status ``deadline_expired`` and whatever
        tokens it produced. With resilience on, the admission gate may
        also refuse the request outright — the returned rid then maps to
        a terminal ``results`` record with status ``shed``."""
        prompt = [int(t) for t in np.asarray(prompt).reshape(-1)]
        if not prompt:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError(f"max_new_tokens must be >= 1, got "
                             f"{max_new_tokens}")
        if len(prompt) > self.bucket_cap:
            raise ValueError(
                f"prompt ({len(prompt)}) exceeds the largest prefill "
                f"bucket ({self.bucket_cap})")
        if len(prompt) + int(max_new_tokens) > self.max_model_len:
            raise ValueError(
                f"prompt ({len(prompt)}) + max_new_tokens "
                f"({max_new_tokens}) exceeds max_model_len "
                f"({self.max_model_len})")
        bs = self.block_size
        # Lifetime KV need: the LAST sampled token's KV is never written
        # (the run ends on it), so the highest write position is
        # prompt + max_new_tokens - 2.
        need = max(self._bucket_of(len(prompt)) // bs,
                   -(-(len(prompt) + int(max_new_tokens) - 1) // bs))
        if need > self.pool.capacity:
            raise ValueError(
                f"request needs {need} KV blocks but the pool holds "
                f"{self.pool.capacity} — it could never be admitted; "
                f"raise serving.kv_num_blocks")
        if deadline_ms is not None:
            if self._resil is None:
                raise ValueError(
                    "deadline_ms requires serving.resilience.enabled "
                    "(docs/SERVING.md 'Serving under failure')")
            if deadline_ms <= 0:
                raise ValueError(
                    f"deadline_ms must be > 0, got {deadline_ms}")
        eos = eos_token_id if eos_token_id is not None \
            else self.scfg.eos_token_id
        if self._fault is not None:
            self._storm_template = (list(prompt), int(max_new_tokens),
                                    eos_token_id, deadline_ms)
        if self._resil is not None:
            reason = self._resil.admission_gate(prompt,
                                                int(max_new_tokens))
            if reason is not None:
                return self._resil.shed(prompt, int(max_new_tokens),
                                        eos, reason)
        rid = self.sched.submit(prompt, int(max_new_tokens), eos)
        req = self.sched.waiting[-1]
        if self._resil is not None:
            dl = (deadline_ms if deadline_ms is not None
                  else self.scfg.resil_default_deadline_ms)
            if dl is not None:
                req.deadline = req.arrival + dl / 1e3
        if self._req_acc is not None:
            self._req_acc.on_submit(req)
        return rid

    def cancel(self, rid: int) -> bool:
        """Flag a submitted request for cancellation; it is resolved at
        the next step boundary — dropped from the queue, or aborted with
        its partial output and terminal status ``cancelled``. Returns
        False when the rid is unknown or already terminal. Requires
        ``serving.resilience``."""
        if self._resil is None:
            raise RuntimeError(
                "cancel() requires serving.resilience.enabled "
                "(docs/SERVING.md 'Serving under failure')")
        return self._resil.request_cancel(rid)

    def idle(self) -> bool:
        return self.sched.idle()

    # ------------------------------------------------------------------
    # the serving step
    # ------------------------------------------------------------------
    def step(self) -> Dict[str, Any]:
        """One engine iteration: admit+prefill (bounded), then advance the
        whole decode batch one token. Returns a step report
        (``finished``/``prefilled`` request ids, ``active`` count...)."""
        info: Dict[str, Any] = {"step": self._step_count, "prefilled": [],
                                "finished": [], "active": 0}
        # Engine serving-time partition (telemetry/requests.py): the
        # accountant's single cursor is advanced at each phase boundary,
        # so the step's wall clock lands in exactly one category. A step
        # that grew a jit cache files its dispatch under "compile" (the
        # first trace dominates that step's wall time).
        acc = self._req_acc
        if acc is not None:
            acc.engine_mark("host_idle")    # since the previous step

        # -- resilience boundary: deadlines/cancellations resolve, then
        # any scheduled chaos storm joins the queue (through submit(),
        # i.e. through the shed gate) ----------------------------------
        if self._resil is not None:
            self._resil.process_boundary()
        if self._fault is not None \
                and self._fault.should_serve_storm(self._step_count):
            self._inject_storm()

        # -- admission + prefill (the in-flight batching half) ----------
        for _ in range(self.scfg.max_prefills_per_step):
            seq = self.sched.try_admit(self._bucket_of, self._step_count)
            if seq is None:
                break
            if self._chunked:
                # Chunked admission: no prefill dispatch here — the
                # prompt enters the mixed program in budget-bounded
                # chunks starting at the adopted prefix head. First
                # token, prefix registration and the ``prefilled``
                # report land when the LAST chunk completes
                # (_mixed_round).
                seq.pos = seq.prefilled = seq.shared_len
                if acc is not None:
                    acc.engine_mark("scheduler_admission")
                self.stats["slot_assignments"].setdefault(seq.slot, 0)
                self.stats["slot_assignments"][seq.slot] += 1
                continue
            if acc is not None:
                acc.engine_mark("scheduler_admission")
                n_jits = len(self._prefill_jit) + len(self._tail_prefill_jit)
            self._prefill(seq)
            if acc is not None:
                grew = (len(self._prefill_jit)
                        + len(self._tail_prefill_jit)) > n_jits
                acc.engine_mark("compile" if grew else "prefill")
                acc.on_prefilled(seq)
            self.sched.register_prefix(seq, self._step_count)
            info["prefilled"].append(seq.request.rid)
            self.stats["slot_assignments"].setdefault(seq.slot, 0)
            self.stats["slot_assignments"][seq.slot] += 1
            if seq.finished():      # max_new_tokens == 1 / instant EOS
                self._finish(seq, info)

        # -- decode one token for every running sequence ----------------
        # (a speculative round writes k+1 positions, so capacity is
        # ensured with that lookahead — capped at each row's lifetime)
        active = self.sched.active
        for seq in list(active):
            if self.sched.running.get(seq.slot) is seq:
                self.sched.ensure_capacity(seq, lookahead=self._spec_k)
        active = self.sched.active          # preemption may have evicted
        info["active"] = len(active)
        if acc is not None:
            acc.engine_mark("scheduler_admission")
        dt_decode = 0.0
        n_tokens = 0
        if active:
            if acc is not None:
                n_djits = (len(self._decode_jits) + len(self._spec_jits)
                           + int(self._mixed_jit is not None))
            if self._resil is not None:
                n_tokens, dt_decode, active = self._resil.run_decode(
                    active, info)
                self._resil.note_step(dt_decode)
            else:
                n_tokens, dt_decode = self._decode_round(active, info)
            if acc is not None:
                grew = (len(self._decode_jits) + len(self._spec_jits)
                        + int(self._mixed_jit is not None)) > n_djits
                acc.engine_mark("compile" if grew else "decode")
                still = [s for s in active
                         if self.sched.running.get(s.slot) is s]
                acc.on_decode_step(still, dt_decode, self._step_count)
            self.stats["decode_steps"] += 1
            self.stats["occupancy_sum"] += \
                len(active) / self.scfg.max_batch_size
            # Cumulative decode rate lives OUTSIDE the telemetry gate:
            # the admission gate's projected-wait fallback needs it even
            # on a telemetry-free engine (two host floats, no syncs).
            if n_tokens and dt_decode > 0:
                self._decode_tokens += n_tokens
                self._decode_sec += dt_decode
        # Gauges carry the SAME step index as this iteration's TTFT/
        # completion rows (emitted above) — increment only afterwards.
        self._emit_step_metrics(len(active), dt_decode, n_tokens)
        self._step_count += 1
        return info

    def run_until_complete(self, max_steps: int = 100_000,
                           timeout_sec: Optional[float] = None
                           ) -> Dict[int, Any]:
        """Drive ``step()`` until every submitted request has finished;
        returns the results map (rid -> record). ``timeout_sec`` is a
        wall-clock bound: a wedged loop (a straggling dispatch, a stuck
        backend) raises loudly with queue/active diagnostics instead of
        spinning toward the step bound at whatever pace the wedge
        allows."""
        steps = 0
        t0 = time.monotonic()
        while not self.idle():
            self.step()
            steps += 1
            if steps > max_steps:
                raise RuntimeError(
                    f"serving did not drain in {max_steps} steps "
                    f"(queue={self.sched.queue_depth}, "
                    f"running={len(self.sched.running)})")
            if timeout_sec is not None \
                    and time.monotonic() - t0 > timeout_sec:
                waiting = [r.rid for r in self.sched.waiting]
                running = {s.slot: s.request.rid
                           for s in self.sched.running.values()}
                raise RuntimeError(
                    f"serving wall-clock timeout: not drained after "
                    f"{timeout_sec:.3f}s ({steps} steps, "
                    f"queue={self.sched.queue_depth} "
                    f"rids={waiting[:8]}, running={running})")
        return self.results

    def serve_forever(self, should_stop=None, idle_sleep: float = 0.002):
        """Loop ``step()`` until ``should_stop()`` returns True, sleeping
        briefly when there is no work. The step-driven core stays
        single-threaded; callers submit from other threads freely (the
        scheduler's deque append is atomic)."""
        while should_stop is None or not should_stop():
            if self.idle():
                if should_stop is None:
                    return          # nothing queued and no stop predicate
                time.sleep(idle_sleep)
                continue
            self.step()

    # ------------------------------------------------------------------
    # internals
    # ------------------------------------------------------------------
    def _bucket_of(self, t: int) -> int:
        if self._chunked:
            # Chunked admission sizes exactly (whole blocks, no pow2
            # rounding): there are no per-bucket compiles to amortize —
            # the ragged program takes any length — so neither KV
            # blocks nor prefill compute ever pay bucket rounding.
            return min(-(-t // self.block_size) * self.block_size,
                       self.bucket_cap)
        b = bucket_length(t, cap=self.bucket_cap)
        b = -(-b // self.block_size) * self.block_size   # whole blocks
        return min(max(b, -(-t // self.block_size) * self.block_size),
                   self.bucket_cap)

    @property
    def mean_occupancy(self) -> float:
        n = self.stats["decode_steps"]
        return self.stats["occupancy_sum"] / n if n else 0.0

    def _result_record(self, seq: Sequence, status: str) -> Dict[str, Any]:
        """Terminal record for an ADMITTED sequence — shared by the
        happy path (``finished``) and the resilience terminals
        (``deadline_expired``/``cancelled``/``aborted``), so the record
        shape cannot drift between them. Latency fields are stamped
        unconditionally — host floats the caller gets without telemetry
        enabled."""
        req = seq.request
        now = time.monotonic()
        return {
            "tokens": list(seq.tokens),
            "prompt_len": len(req.prompt),
            "status": status,
            "slot": seq.slot,
            "finish_step": self._step_count,
            "ttft_ms": (req.first_token_time - req.arrival) * 1e3
            if req.first_token_time else None,
            "finish_time": now,
            "e2e_ms": (now - req.arrival) * 1e3,
            "queue_wait_ms": (req.admitted_time - req.arrival) * 1e3
            if req.admitted_time is not None else None,
            "preempted_count": req.preempted_count,
        }

    def _queue_record(self, req, status: str,
                      reason: Optional[str] = None) -> Dict[str, Any]:
        """Terminal record for a request that was NEVER admitted (shed,
        cancelled/expired in the queue, torn down with the engine):
        ``tokens`` is just the prompt, TTFT/queue-wait never existed."""
        now = time.monotonic()
        rec = {
            "tokens": list(req.prompt),
            "prompt_len": len(req.prompt),
            "status": status,
            "slot": None,
            "finish_step": self._step_count,
            "ttft_ms": None,
            "finish_time": now,
            "e2e_ms": (now - req.arrival) * 1e3,
            "queue_wait_ms": None,
            "preempted_count": req.preempted_count,
        }
        if reason is not None:
            rec["shed_reason"] = reason
        return rec

    def _finish(self, seq: Sequence, info: Dict[str, Any]) -> None:
        rid = seq.request.rid
        self.sched.finish(seq)
        self.results[rid] = self._result_record(seq, "finished")
        info["finished"].append(rid)
        tel = self.telemetry
        if tel.enabled:
            tel.registry.counter("serving/requests_completed").inc(
                step=self._step_count)
        if self._req_acc is not None:
            slo = self._req_acc.on_finish(seq, self._step_count)
            if slo is not None:
                self.results[rid]["slo"] = slo

    # -- prefill --------------------------------------------------------
    def _prefill(self, seq: Sequence) -> None:
        if seq.shared_len:
            # Warm prompt head (prefix cache hit): the adopted blocks
            # already hold positions [0, shared_len) — only the tail is
            # computed, through the paged cache (TTFT collapses to the
            # unshared remainder).
            self._prefill_tail(seq)
            return
        t = len(seq.request.prompt)
        bucket = seq.bucket
        ids = np.zeros((1, bucket), np.int32)
        ids[0, :t] = seq.request.prompt          # right-pad: causal masking
        dev_ids = jnp.asarray(ids)
        length = jnp.asarray(t, jnp.int32)       # keeps pads invisible
        rng = jax.random.fold_in(self._base_key, 2 * seq.request.rid + 1)
        # Per-bucket detector scope: each bucket's one compile is the
        # expected first trace, so a healthy engine never warns — a
        # retrace under any of these names is a real bug.
        self.engine.recompile_detector.check(
            f"serving.prefill_b{bucket}", dev_ids, length)
        if bucket not in self._prefill_jit:
            self._prefill_jit[bucket] = jax.jit(functools.partial(
                self._prefill_impl, bucket=bucket))
        with self.telemetry.span("prefill", rid=seq.request.rid,
                                 bucket=bucket, prompt_len=t):
            tok, _logits, ks, vs = self._prefill_jit[bucket](
                self.engine.params, dev_ids, length, rng)
            if self._measure_kv:
                self._emit_kv_quant_error(ks, vs, length, bucket)
            blocks = jnp.asarray(seq.block_table, jnp.int32)
            self._pools = self._pack_jit(self._pools, blocks, ks, vs)
            first = int(tok)                     # host fetch = first token
        self._record_first_token(seq, first)

    def _prefill_tail(self, seq: Sequence) -> None:
        """Prefill only the unshared prompt tail through the paged cache:
        the tail chunk (right-padded to a block-multiple bucket) runs one
        multi-token paged forward at per-row position ``shared_len`` —
        writes land past the adopted (immutable) head blocks, attention
        sees head + causal tail, and the first token samples from the
        last REAL tail position. The int8 KV quant-error gauge is NOT
        measured here: the adopted head blocks were measured at their
        cold prefill, and the tail's K/V never leave the jitted program
        as stacks (docs/SERVING.md "Current limits")."""
        t = len(seq.request.prompt)
        sl = seq.shared_len
        tail = t - sl                           # >= 1 (match is capped)
        mb_positions = self.max_blocks * self.block_size
        tb = min(self._bucket_of(tail), mb_positions - sl)
        ids = np.zeros((1, tb), np.int32)
        ids[0, :tail] = seq.request.prompt[sl:]
        bt = np.zeros((1, self.max_blocks), np.int32)
        bt[0, :len(seq.block_table)] = seq.block_table
        dev_ids, dev_bt = jnp.asarray(ids), jnp.asarray(bt)
        start = jnp.asarray([sl], jnp.int32)
        length = jnp.asarray(tail, jnp.int32)
        rng = jax.random.fold_in(self._base_key, 2 * seq.request.rid + 1)
        self.engine.recompile_detector.check(
            f"serving.prefill_tail_b{tb}", dev_ids, dev_bt, start, length)
        if tb not in self._tail_prefill_jit:
            self._tail_prefill_jit[tb] = jax.jit(functools.partial(
                self._prefill_tail_impl, tail_bucket=tb),
                donate_argnums=(1,))
        with self.telemetry.span("prefill", rid=seq.request.rid,
                                 bucket=tb, prompt_len=t, shared_len=sl):
            tok, self._pools = self._tail_prefill_jit[tb](
                self.engine.params, self._pools, dev_ids, dev_bt, start,
                length, rng)
            first = int(tok)                     # host fetch = first token
        self._record_first_token(seq, first)

    def _record_first_token(self, seq: Sequence, first: int) -> None:
        """Append the prefill's sampled token and record TTFT — on the
        request's FIRST prefill only: a preemption restart (cold or
        warm) must not add a second (optimistically small) TTFT
        observation."""
        now = time.monotonic()
        seq.tokens.append(first)
        if seq.request.first_token_time is None:
            seq.request.first_token_time = now
            if self.telemetry.enabled:
                self.telemetry.registry.histogram(
                    "serving/ttft_ms").observe(
                    (now - seq.request.arrival) * 1e3,
                    step=self._step_count)

    def _replay_prefill(self, seq: Sequence, replay: List[int]) -> None:
        """Recovery replay (serving/resilience.py): rebuild ``seq``'s KV
        ``[0, pos)`` in the fresh pools by prefilling its recorded
        ``tokens[:-1]`` — through the SAME per-bucket prefill programs
        as a cold/warm admission (pure functions, kept across the
        rebuild). The sampled token is discarded: under greedy it equals
        the already-recorded ``tokens[-1]``, whose KV is written by the
        next decode step as usual. No TTFT observation, no token
        append, no quant-error measure — the request already paid its
        real prefill."""
        if self._chunked:
            self._replay_chunked(seq, replay)
            return
        t = len(replay)
        rng = jax.random.fold_in(self._base_key, 2 * seq.request.rid + 1)
        if seq.shared_len:
            sl = seq.shared_len
            tail = t - sl
            mb_positions = self.max_blocks * self.block_size
            tb = min(self._bucket_of(tail), mb_positions - sl)
            ids = np.zeros((1, tb), np.int32)
            ids[0, :tail] = replay[sl:]
            bt = np.zeros((1, self.max_blocks), np.int32)
            bt[0, :len(seq.block_table)] = seq.block_table
            dev_ids, dev_bt = jnp.asarray(ids), jnp.asarray(bt)
            start = jnp.asarray([sl], jnp.int32)
            length = jnp.asarray(tail, jnp.int32)
            self.engine.recompile_detector.check(
                f"serving.prefill_tail_b{tb}", dev_ids, dev_bt, start,
                length)
            if tb not in self._tail_prefill_jit:
                self._tail_prefill_jit[tb] = jax.jit(functools.partial(
                    self._prefill_tail_impl, tail_bucket=tb),
                    donate_argnums=(1,))
            with self.telemetry.span("prefill", rid=seq.request.rid,
                                     bucket=tb, prompt_len=t, replay=1):
                _tok, self._pools = self._tail_prefill_jit[tb](
                    self.engine.params, self._pools, dev_ids, dev_bt,
                    start, length, rng)
            return
        bucket = seq.bucket
        ids = np.zeros((1, bucket), np.int32)
        ids[0, :t] = replay
        dev_ids = jnp.asarray(ids)
        length = jnp.asarray(t, jnp.int32)
        self.engine.recompile_detector.check(
            f"serving.prefill_b{bucket}", dev_ids, length)
        if bucket not in self._prefill_jit:
            self._prefill_jit[bucket] = jax.jit(functools.partial(
                self._prefill_impl, bucket=bucket))
        with self.telemetry.span("prefill", rid=seq.request.rid,
                                 bucket=bucket, prompt_len=t, replay=1):
            _tok, _logits, ks, vs = self._prefill_jit[bucket](
                self.engine.params, dev_ids, length, rng)
            blocks = jnp.asarray(seq.block_table, jnp.int32)
            self._pools = self._pack_jit(self._pools, blocks, ks, vs)

    def _replay_chunked(self, seq: Sequence, replay: List[int]) -> None:
        """Chunked-mode replay: rebuild ``[shared_len, len(replay))`` in
        the fresh pools through the SAME mixed program as live traffic —
        no per-bucket replay variants to compile. Samples are discarded
        (greedy: they equal the recorded tokens); the seq's cursors
        already reflect its pre-crash state, only pool contents need
        rebuilding. Resilience only routes fully-prefilled sequences
        here (a mid-prefill seq is cold-requeued instead)."""
        t0, total = seq.shared_len, len(replay)
        while t0 < total:
            c = min(self._chunk_budget, total - t0)
            rows = [(seq.slot, replay[t0 + i], t0 + i) for i in range(c)]
            with self.telemetry.span("prefill", rid=seq.request.rid,
                                     bucket=seq.bucket, prompt_len=total,
                                     replay=1):
                self._mixed_dispatch([seq], rows, 1)
            t0 += c

    def _prefill_tail_impl(self, params, pools, ids, bt, start, length,
                           rng, *, tail_bucket: int):
        # The tail writes [start, start + tail_bucket) — block-aligned
        # start, so adopted head blocks are never touched; pad positions
        # past the allocated blocks hit zero table entries (scratch).
        cache = tuple(
            PagedLayerCache(*pools[i], bt, start, self.block_size,
                            self._dtype_name)
            for i in range(self.model_cfg.num_layers))
        pos_ids = jnp.minimum(start[:, None] + jnp.arange(tail_bucket),
                              self.model_cfg.max_seq_len - 1)
        out = self.module.apply(
            {"params": self.engine._materialized(params)},
            {"input_ids": ids, "position_ids": pos_ids},
            deterministic=True, cache=cache, pos=None)
        last = jax.lax.dynamic_index_in_dim(out["logits"], length - 1,
                                            axis=1, keepdims=False)  # [1,V]
        tok = sample_logits(last.astype(jnp.float32), rng,
                            self.scfg.temperature, self.scfg.top_k)[0]
        return tok, tuple(c.pools for c in out["cache"])

    def _prefill_impl(self, params, ids, length, rng, *, bucket: int):
        from deepspeed_tpu.models.gpt import init_kv_cache

        cache = init_kv_cache(self.model_cfg, 1, bucket, dtype=self._dtype)
        out = self.module.apply(
            {"params": self.engine._materialized(params)},
            {"input_ids": ids}, deterministic=True, cache=cache, pos=0)
        # Right-padded prompt: causality alone keeps pad positions out of
        # every real token's attention, so the last REAL position's logits
        # are exact; pad-position K/V are garbage the position mask hides.
        last = jax.lax.dynamic_index_in_dim(out["logits"], length - 1,
                                            axis=1, keepdims=False)  # [1,V]
        tok = sample_logits(last.astype(jnp.float32), rng,
                            self.scfg.temperature, self.scfg.top_k)[0]
        k_stack = jnp.stack([c[0][0] for c in out["cache"]])  # [L,Tb,H,D]
        v_stack = jnp.stack([c[1][0] for c in out["cache"]])
        return tok, last, k_stack, v_stack

    # -- decode ---------------------------------------------------------
    def _decode_round(self, active: List[Sequence],
                      info: Dict[str, Any]):
        """One decode (or speculative) round for the batch: dispatch,
        append accepted tokens, finish rows that completed. Returns
        ``(n_tokens, dt_decode)`` — the dispatch+fetch wall seconds the
        throughput gauge and the accountant both key on. Host-side
        extraction of the step() decode block (the lowered programs are
        untouched); the resilience manager wraps THIS boundary, where a
        failed dispatch has mutated nothing."""
        t_dec = time.perf_counter()
        if self._chunked:
            # The mixed ragged program serves every round that has a
            # prefill chunk in flight — and, without speculative
            # decoding, every round (the all-decode batch is just the
            # degenerate ragged case; one program ever). With spec on,
            # rounds with no chunk in flight fall through to the
            # speculative path (greedy-identical either way).
            prefilling = any(s.prefilled < len(s.request.prompt)
                             for s in active)
            if prefilling or not self._spec_k:
                n_tokens = self._mixed_round(active, info)
                return n_tokens, time.perf_counter() - t_dec
        if self._spec_k:
            n_tokens = self._spec_round(active, info)
            dt_decode = time.perf_counter() - t_dec
        else:
            toks, logits = self._decode(active)
            dt_decode = time.perf_counter() - t_dec
            n_tokens = len(active)
            for seq, tok in zip(active, toks):
                seq.tokens.append(int(tok))
                seq.pos += 1
                if seq.finished():
                    self._finish(seq, info)
            if self.capture_logits:
                info["logits"] = logits
                info["slots"] = {s.slot: s.request.rid for s in active}
        return n_tokens, dt_decode

    def _inject_storm(self) -> None:
        """FaultPlan request storm: a burst of duplicates of the last
        submitted request, through the normal ``submit()`` path — i.e.
        through the shed gate when resilience is on (the overload
        scenario the admission controller exists for)."""
        if self._storm_template is None:
            return
        prompt, max_new, eos, dl = self._storm_template
        n = self._fault.serve_storm_requests
        log_dist(f"serving: FaultPlan request storm — {n} burst "
                 f"submissions at step {self._step_count}", ranks=[0])
        for _ in range(n):
            if self._resil is not None:
                self.submit(prompt, max_new, eos, deadline_ms=dl)
            else:
                self.submit(prompt, max_new, eos)

    def _fault_hook(self) -> None:
        """Serving chaos rides the decode DISPATCH attempt counter:
        monotonic across steps AND retries, so a fault window of width k
        is consumed by k dispatch attempts (a transient fault heals
        under retry; a wider window forces the rebuild path). Raising
        here mutates nothing — pools are only donated by a dispatch
        that actually runs. Shared by the bucketed/spec dispatch prep
        and the chunked mixed dispatch, so chaos covers all three."""
        if self._fault is None:
            return
        self._dispatch_attempts += 1
        if self._fault.should_serve_decode_fault(self._dispatch_attempts):
            self._fault.serve_decode_fault(self._dispatch_attempts)
        if self._fault.should_serve_slow_step(self._dispatch_attempts):
            self._fault.serve_slow_step()

    def _batch_inputs(self, active: List[Sequence]):
        """Host-side decode batch matrices (inactive rows -> scratch)."""
        nb, mb = self.scfg.max_batch_size, self.max_blocks
        bt = np.zeros((nb, mb), np.int32)
        pos = np.zeros((nb,), np.int32)
        toks = np.zeros((nb,), np.int32)
        for seq in active:
            s = seq.slot
            bt[s, :len(seq.block_table)] = seq.block_table
            pos[s] = seq.pos
            toks[s] = seq.tokens[-1]
        return bt, pos, toks

    def _window_blocks(self, active: List[Sequence], chunk: int) -> int:
        """Fast-path key-window width: enough table columns to cover the
        longest active row's reads AND the chunk's writes, ceiled to a
        power of two — O(log max_blocks) compiled decode variants, each
        gathering/streaming only what some batch actually needs."""
        need_pos = max(seq.pos for seq in active) + chunk
        need = -(-need_pos // self.block_size)
        wb = 1
        while wb < need:
            wb *= 2
        return min(wb, self.max_blocks)

    def _dispatch_batch(self, active: List[Sequence], chunk: int,
                        scope: str):
        """Shared decode/spec dispatch prep: batch matrices, window
        slicing under the fast path, the detector scope (per window
        bucket when capped), the jit-cache key, the resolved attention
        impl, and the gathered-positions evidence — ONE accounting for
        both paths so they cannot drift."""
        self._fault_hook()
        mb = self.max_blocks
        bt, pos, toks = self._batch_inputs(active)
        if self._fast_path:
            wb = self._window_blocks(active, chunk)
            bt = bt[:, :wb]
            key, name, impl = wb, f"{scope}_w{wb}", self._attn_impl
        else:
            wb, key, name, impl = mb, None, scope, "gather"
        self.stats["gathered_positions"] += wb * self.block_size
        self.stats["full_positions"] += mb * self.block_size
        if impl == "kernel":
            self.stats["kernel_steps"] += 1
        bt, pos, toks = jnp.asarray(bt), jnp.asarray(pos), jnp.asarray(toks)
        self.engine.recompile_detector.check(name, toks, pos, bt)
        return bt, pos, toks, key, impl

    def _decode(self, active: List[Sequence]):
        bt, pos, toks, key, impl = self._dispatch_batch(
            active, 1, "serving.decode_step")
        rng = jax.random.fold_in(self._base_key, 2 * self._step_count)
        if key not in self._decode_jits:
            self._decode_jits[key] = jax.jit(
                functools.partial(self._decode_impl, attn_impl=impl),
                donate_argnums=(1,))
        with self.telemetry.span("decode_step", active=len(active)):
            tok_dev, logits, self._pools = self._decode_jits[key](
                self.engine.params, self._pools, bt, pos, toks, rng)
            tok_host = np.asarray(tok_dev)       # host fetch: finish checks
        logits_host = np.asarray(logits) if self.capture_logits else None
        return [int(tok_host[s.slot]) for s in active], logits_host

    def _decode_impl(self, params, pools, bt, pos, toks, rng, *,
                     attn_impl: str = "gather"):
        cache = tuple(
            PagedLayerCache(*pools[i], bt, pos, self.block_size,
                            self._dtype_name, attn_impl)
            for i in range(self.model_cfg.num_layers))
        out = self.module.apply(
            {"params": self.engine._materialized(params)},
            {"input_ids": toks[:, None], "position_ids": pos[:, None]},
            deterministic=True, cache=cache, pos=None)
        logits = out["logits"][:, -1].astype(jnp.float32)
        tok = sample_logits(logits, rng, self.scfg.temperature,
                            self.scfg.top_k)
        return tok, logits, tuple(c.pools for c in out["cache"])

    # -- chunked prefill (the mixed ragged round) -----------------------
    def _mixed_round(self, active: List[Sequence],
                     info: Dict[str, Any]) -> int:
        """One mixed step: every decoding sequence advances one token
        AND waiting prompts prefill in chunks, all through ONE ragged
        program. Rows: decode tokens first (each decoding slot must
        advance — the token budget is validated >= max_batch_size),
        then prefill chunks FCFS by admission until the budget is full.
        A prompt whose last chunk lands this step samples its first
        token from that chunk's final row — exactly the logits the
        bucketed prefill samples from, so outputs are token-identical.
        Returns the number of tokens appended."""
        if self.capture_logits:
            raise ValueError(
                "capture_logits is not supported with chunked prefill — "
                "a mixed step has no per-slot logits row to expose "
                "(docs/SERVING.md)")
        decoding = [s for s in active
                    if s.prefilled >= len(s.request.prompt)]
        prefilling = sorted(
            (s for s in active if s.prefilled < len(s.request.prompt)),
            key=lambda s: (s.admitted_step, s.request.rid))
        self._fault_hook()   # live rounds only — replay never injects
        rows = [(s.slot, s.tokens[-1], s.pos) for s in decoding]
        chunks = []                              # (seq, first_row, count)
        for s in prefilling:
            if len(rows) >= self._chunk_budget:
                break
            t0 = s.prefilled
            c = min(len(s.request.prompt) - t0,
                    self._chunk_budget - len(rows))
            chunks.append((s, len(rows), c))
            rows.extend((s.slot, s.request.prompt[t0 + i], t0 + i)
                        for i in range(c))
        tok_host = self._mixed_dispatch(active, rows, len(active))
        self._chunk_tokens_last = len(rows)
        appended = 0
        for r, seq in enumerate(decoding):
            seq.tokens.append(int(tok_host[r]))
            seq.pos += 1
            appended += 1
            if seq.finished():
                self._finish(seq, info)
        for seq, r0, c in chunks:
            seq.prefilled += c
            seq.pos = seq.prefilled
            if seq.prefilled == len(seq.request.prompt):
                # Prompt complete: the chunk's final row sits at the
                # last prompt position — its sampled token is the first
                # generated token (TTFT lands here).
                self._record_first_token(seq, int(tok_host[r0 + c - 1]))
                appended += 1
                if self._req_acc is not None:
                    self._req_acc.on_prefilled(seq)
                self.sched.register_prefix(seq, self._step_count)
                info["prefilled"].append(seq.request.rid)
                if seq.finished():   # max_new_tokens == 1 / instant EOS
                    self._finish(seq, info)
        return appended

    def _mixed_dispatch(self, table_seqs: List[Sequence], rows,
                        n_active: int):
        """Dispatch one ragged token batch. ``rows``: ``(slot, token,
        position)`` triples (decode rows then chunk rows); the batch is
        padded to the token budget with scratch rows — slot
        ``max_batch_size`` maps to the spare all-zeros table row, so pad
        writes land in the reserved scratch block and pad reads stay
        masked. ONE detector scope, ONE jit entry, ever: every mixed
        step has the same signature regardless of the decode/prefill
        mix."""
        nb, mb = self.scfg.max_batch_size, self.max_blocks
        bt = np.zeros((nb + 1, mb), np.int32)    # row nb: pad/scratch row
        toks = np.zeros((self._chunk_budget,), np.int32)
        pos = np.zeros((self._chunk_budget,), np.int32)
        slots = np.full((self._chunk_budget,), nb, np.int32)
        for seq in table_seqs:
            bt[seq.slot, :len(seq.block_table)] = seq.block_table
        for r, (sl, tk, p) in enumerate(rows):
            slots[r], toks[r], pos[r] = sl, tk, p
        bt, pos, toks, slots = (jnp.asarray(bt), jnp.asarray(pos),
                                jnp.asarray(toks), jnp.asarray(slots))
        self.engine.recompile_detector.check("serving.mixed_step", toks,
                                             pos, slots, bt)
        if self._mixed_jit is None:
            self._mixed_jit = jax.jit(self._mixed_impl,
                                      donate_argnums=(1,))
        rng = jax.random.fold_in(self._base_key, 2 * self._step_count)
        with self.telemetry.span("mixed_step", active=n_active,
                                 tokens=len(rows)):
            tok_dev, self._pools = self._mixed_jit(
                self.engine.params, self._pools, bt, pos, slots, toks,
                rng)
            tok_host = np.asarray(tok_dev)       # host fetch: finish checks
        return tok_host

    def _mixed_impl(self, params, pools, bt, pos, slots, toks, rng):
        max_pos = self.model_cfg.max_seq_len - 1
        cache = tuple(
            ChunkedLayerCache(*pools[i], bt, slots, pos, self.block_size,
                              self._dtype_name)
            for i in range(self.model_cfg.num_layers))
        out = self.module.apply(
            {"params": self.engine._materialized(params)},
            {"input_ids": toks[None, :],
             "position_ids": jnp.minimum(pos, max_pos)[None, :]},
            deterministic=True, cache=cache, pos=None)
        logits = out["logits"][0].astype(jnp.float32)      # [T, V]
        tok = sample_logits(logits, rng, self.scfg.temperature,
                            self.scfg.top_k)
        return tok, tuple(c.pools for c in out["cache"])

    # -- speculative decoding -------------------------------------------
    def _init_speculative(self) -> None:
        """Draft model = a truncated-layer view of the target (the
        config-named default): the first ``draft_layers`` blocks plus the
        shared embeddings/final-LN/head, applied with the SAME params by
        top-level key. Because the draft's layer stack IS the target's
        prefix, its per-layer K/V are identical to the target's for the
        same inputs — so the draft reads and writes the target's own
        pools for its layers: no second KV cache, no draft prefill, and
        the verify step's rewrites are bit-identical no-ops for accepted
        tokens."""
        from dataclasses import replace as dc_replace

        cfg = self.model_cfg
        if self.scfg.temperature != 0.0:
            raise ValueError("speculative decoding requires greedy "
                             "sampling (serving.temperature == 0)")
        dl = (self.scfg.spec_draft_layers
              if self.scfg.spec_draft_layers is not None
              else max(1, cfg.num_layers // 2))
        if not 1 <= dl < cfg.num_layers:
            raise ValueError(
                f"serving.speculative.draft_layers must be in "
                f"[1, {cfg.num_layers - 1}] for a {cfg.num_layers}-layer "
                f"target, got {dl}")
        self._spec_k = int(self.scfg.spec_k)
        self._draft_layers = dl
        self._draft_module = type(self.module)(
            dc_replace(cfg, num_layers=dl))
        keys = ["wte", "wpe", "ln_f"] + [f"h_{i}" for i in range(dl)]
        if not getattr(cfg, "tie_embeddings", True):
            keys.append("lm_head")
        self._draft_param_keys = tuple(keys)
        log_dist(f"serving: speculative decode on — draft = first {dl}/"
                 f"{cfg.num_layers} layers, k={self._spec_k}", ranks=[0])

    def _spec_round(self, active: List[Sequence],
                    info: Dict[str, Any]) -> int:
        """One speculative round for the whole batch: the draft proposes
        ``k`` tokens (one jitted scan — its writes land in the shared
        pools), ONE target verification scores all ``k+1`` positions
        through the paged cache, and the standard greedy accept rule
        keeps outputs token-identical to non-speculative decode: a draft
        token is kept iff it equals the target's greedy choice at that
        position, and the first disagreement is replaced by the target's
        own token. Rejected positions simply stay behind the write
        cursor (``seq.pos``) — masked now, overwritten by the next
        round's chunk. Returns the number of tokens appended."""
        if self.capture_logits:
            raise ValueError(
                "capture_logits is not supported with speculative "
                "decoding — a spec round has no single per-step logits "
                "row to expose (docs/SERVING.md)")
        k = self._spec_k
        bt, pos, toks, key, impl = self._dispatch_batch(
            active, k + 1, "serving.spec_step")
        if key not in self._spec_jits:
            self._spec_jits[key] = jax.jit(
                functools.partial(self._spec_impl, k=k, attn_impl=impl),
                donate_argnums=(1,))
        with self.telemetry.span("spec_step", active=len(active), k=k):
            chunk_dev, greedy_dev, self._pools = self._spec_jits[key](
                self.engine.params, self._pools, bt, pos, toks)
            chunk = np.asarray(chunk_dev)        # [B, k+1] verify inputs
            greedy = np.asarray(greedy_dev)      # [B, k+1] target argmax
        appended = 0
        for seq in active:
            s = seq.slot
            drafted = chunk[s, 1:]               # d_1..d_k
            target = greedy[s]                   # g_1..g_{k+1}
            accept = 0
            while accept < k and int(drafted[accept]) == int(target[accept]):
                accept += 1
            self.stats["spec_proposed"] += k
            self.stats["spec_accepted"] += accept
            # d_1..d_a are the target's own greedy tokens (they matched);
            # g_{a+1} is the correction/bonus — every appended token is
            # exactly what greedy non-speculative decode would emit.
            for tok in list(drafted[:accept]) + [target[accept]]:
                seq.tokens.append(int(tok))
                seq.pos += 1
                appended += 1
                if seq.finished():
                    self._finish(seq, info)
                    break
        self.stats["spec_rounds"] += 1
        self.stats["spec_new_tokens"] += appended
        return appended

    def _spec_impl(self, params, pools, bt, pos, toks, *, k: int,
                   attn_impl: str):
        """Draft scan (k+1 single-token steps — the extra step pre-writes
        the full-accept position so the draft cache never lags) + ONE
        multi-query target verification over the chunk ``[t0, d_1..d_k]``
        at positions ``pos..pos+k``. Writes are clamp-guarded: lookahead
        past a row's allocated blocks lands in scratch."""
        p = self.engine._materialized(params)
        dp = {key: p[key] for key in self._draft_param_keys}
        dl = self._draft_layers
        nl = self.model_cfg.num_layers
        bs = self.block_size
        max_pos = self.model_cfg.max_seq_len - 1

        def draft_step(carry, j):
            pools_c, cur = carry
            cache = tuple(
                PagedLayerCache(*pools_c[i], bt, pos + j, bs,
                                self._dtype_name, attn_impl,
                                clamp_writes=True)
                for i in range(dl))
            out = self._draft_module.apply(
                {"params": dp},
                {"input_ids": cur[:, None],
                 "position_ids": jnp.minimum(pos + j, max_pos)[:, None]},
                deterministic=True, cache=cache, pos=None)
            nxt = jnp.argmax(out["logits"][:, -1].astype(jnp.float32),
                             axis=-1).astype(jnp.int32)
            new_pools = tuple(out["cache"][i].pools if i < dl else pools_c[i]
                              for i in range(nl))
            return (new_pools, nxt), cur

        (pools, _), inputs = jax.lax.scan(draft_step, (pools, toks),
                                          jnp.arange(k + 1))
        chunk = inputs.T                              # [B, k+1] t0,d_1..d_k
        pos_ids = jnp.minimum(pos[:, None] + jnp.arange(k + 1), max_pos)
        cache = tuple(
            PagedLayerCache(*pools[i], bt, pos, bs, self._dtype_name,
                            attn_impl, clamp_writes=True)
            for i in range(nl))
        out = self.module.apply(
            {"params": p}, {"input_ids": chunk, "position_ids": pos_ids},
            deterministic=True, cache=cache, pos=None)
        greedy = jnp.argmax(out["logits"].astype(jnp.float32),
                            axis=-1).astype(jnp.int32)   # [B, k+1]
        return chunk, greedy, tuple(c.pools for c in out["cache"])

    # -- telemetry ------------------------------------------------------
    def _emit_kv_quant_error(self, ks, vs, length, bucket: int) -> None:
        """``numerics/kv_quant_rel_err`` / ``_max_abs_err``: RTNE
        round-trip error of the per-(token, head) int8 quantization the
        pool stores (block = head_dim, the quantize_chunk layout),
        measured over the REAL prompt positions (pads are masked to
        zero, and zero blocks round-trip exactly — they contribute
        nothing to either norm). One jitted measure per prompt bucket
        and ONE device_get for both scalars, on the prefill path that
        already pays a first-token fetch; gated on the numerics opt-in
        (``_measure_kv``). The measured evidence behind the int8-KV
        accuracy/bandwidth trade (docs/OBSERVABILITY.md "Numerics
        observatory")."""
        from deepspeed_tpu.comm.quantize import roundtrip_error

        if bucket not in self._kv_err_jit:
            def measure(ks_, vs_, length_):
                # ks_/vs_: [L, bucket, H, D]; mask pad positions.
                mask = (jnp.arange(ks_.shape[1]) < length_)[None, :, None,
                                                            None]
                kz = jnp.where(mask, ks_.astype(jnp.float32), 0.0)
                vz = jnp.where(mask, vs_.astype(jnp.float32), 0.0)
                head_dim = kz.shape[-1]
                rk, mk = roundtrip_error(kz, 8, head_dim)
                rv, mv = roundtrip_error(vz, 8, head_dim)
                return jnp.maximum(rk, rv), jnp.maximum(mk, mv)

            self._kv_err_jit[bucket] = jax.jit(measure)
        rel, mab = jax.device_get(self._kv_err_jit[bucket](ks, vs, length))
        reg = self.telemetry.registry
        reg.gauge("numerics/kv_quant_rel_err").set(
            float(rel), step=self._step_count, bucket=bucket)
        reg.gauge("numerics/kv_quant_max_abs_err").set(
            float(mab), step=self._step_count, bucket=bucket)

    def _emit_step_metrics(self, n_active: int, dt_decode: float,
                           n_tokens: int) -> None:
        """``dt_decode``: wall seconds of the decode dispatch+fetch only —
        the throughput gauge means DECODE tokens/s, so prefill/admission
        time on the same step must not dilute it. ``n_tokens``: tokens
        appended this step (== active rows, except speculative rounds
        append up to k+1 per row)."""
        tel = self.telemetry
        if not tel.enabled:
            return
        reg = tel.registry
        step = self._step_count
        reg.gauge("serving/batch_occupancy").set(
            n_active / self.scfg.max_batch_size, step=step)
        reg.gauge("serving/kv_blocks_in_use").set(self.pool.used_blocks,
                                                  step=step)
        reg.gauge("serving/queue_depth").set(self.sched.queue_depth,
                                             step=step)
        if n_tokens and dt_decode > 0:
            reg.gauge("serving/tokens_per_sec").set(
                self._decode_tokens / self._decode_sec, step=step)
        # Request observatory rides here (only when the accountant is on,
        # so the telemetry.requests=off tag set stays byte-identical):
        # the rolling-window throughput gauge — responsive under changing
        # load where the cumulative mean above goes stale — plus the
        # requests/* category + engine-partition gauges.
        acc = self._req_acc
        if acc is not None:
            if n_tokens and dt_decode > 0:
                acc.rolling_add(n_tokens, dt_decode)
            rate = acc.rolling_rate()
            if rate is not None:
                reg.gauge("serving/tokens_per_sec_window").set(rate,
                                                               step=step)
            acc.emit(step)
        pre = self.sched.preempted_total
        ctr = reg.counter("serving/preempted_seqs")
        if pre > ctr.total:
            ctr.inc(pre - ctr.total, step=step)
        # -- fast-path attribution (only when the piece is on: the tag
        # set a disabled engine emits is byte-identical to PR 8's) ------
        if self._fast_path and n_active:
            reg.gauge("serving/decode_attn_kernel").set(
                1.0 if self._attn_impl == "kernel" else 0.0, step=step)
        if self.prefix_cache is not None:
            for tag, total in (
                    ("serving/prefix_hits", self.prefix_cache.hits),
                    ("serving/prefix_blocks_reused",
                     self.prefix_cache.blocks_reused)):
                ctr = reg.counter(tag)
                if total > ctr.total:
                    ctr.inc(total - ctr.total, step=step)
        if self._spec_k and self.stats["spec_rounds"]:
            reg.gauge("serving/spec_accept_rate").set(
                self.stats["spec_accepted"]
                / max(1, self.stats["spec_proposed"]), step=step)
            reg.gauge("serving/spec_tokens_per_verify").set(
                self.stats["spec_new_tokens"] / self.stats["spec_rounds"],
                step=step)
        # -- resilience transitions (only when the manager exists: the
        # serving.resilience=off tag set stays byte-identical) ----------
        if self._resil is not None:
            reg.gauge("serving/degraded_level").set(
                self._resil.degraded_level, step=step)
            c = self._resil.counters
            for tag, total in (
                    ("serving/shed_requests", c["shed_requests"]),
                    ("serving/deadline_expired", c["deadline_expired"]),
                    ("serving/cancelled", c["cancelled"]),
                    ("serving/recoveries", c["recoveries"]),
                    ("serving/retries", c["retries"])):
                ctr = reg.counter(tag)
                if total > ctr.total:
                    ctr.inc(total - ctr.total, step=step)
        # -- chunked-prefill admission (only when the mode is on: the
        # serving.chunked_prefill=off tag set stays byte-identical) -----
        if self._chunked:
            reg.gauge("serving/chunked_tokens_per_step").set(
                self._chunk_tokens_last, step=step)
            reg.gauge("serving/prefill_chunks_in_flight").set(
                sum(1 for s in self.sched.running.values()
                    if s.prefilled < len(s.request.prompt)), step=step)

    def close(self) -> None:
        """Flush AND close the telemetry this engine drives (sink file
        handles, tracer, request records) — init_serving hands the
        engine ownership. Any request still in flight or queued gets a
        terminal ``aborted`` record first: every submitted rid resolves
        through ``results``, even through a teardown."""
        for seq in list(self.sched.running.values()):
            rid = seq.request.rid
            self.sched.abort(seq)
            self.results[rid] = self._result_record(seq, "aborted")
            if self._req_acc is not None:
                slo = self._req_acc.on_finish(seq, self._step_count,
                                              status="aborted")
                if slo is not None:
                    self.results[rid]["slo"] = slo
        while self.sched.waiting:
            req = self.sched.waiting.popleft()
            self.results[req.rid] = self._queue_record(req, "aborted")
            if self._req_acc is not None:
                self._req_acc.on_drop(req, "aborted", self._step_count)
        if self._req_acc is not None:
            self._req_acc.close()
        self.telemetry.close()
