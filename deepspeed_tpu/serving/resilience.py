"""Serving resilience — deadlines, admission control, in-flight recovery.

The serving-side counterpart of the training stack's guardrails +
elasticity (PRs 3/13): the ServeEngine owns exactly one
:class:`ResilienceManager` (or ``None`` — the ``serving.resilience`` off
state, which keeps every engine hook a single attribute check and the
emitted tag set + lowered decode program byte-identical). Four composable
pieces, all driven at decode-step boundaries:

- **Deadlines + cancellation** — ``submit(deadline_ms=...)`` stamps an
  absolute monotonic deadline on the request; ``cancel(rid)`` flags one
  for removal. Both resolve at the next step boundary: a queued request
  is dropped without admission, a running sequence is aborted with its
  partial output kept, KV blocks and prefix-cache refs released exactly
  once (``Scheduler.abort`` → ``BlockPool.release``, whose refcounts
  raise on double-free — the leak assertion is structural). Terminal
  statuses: ``deadline_expired`` / ``cancelled``.
- **SLO-aware admission control + load shedding** — at submit time the
  projected queue wait (pending decode tokens over the RequestAccountant
  rolling tokens/s window, falling back to the engine's cumulative rate)
  is compared against ``max_queue_wait_ms``; past it the request is
  **shed**: it gets a real rid, a terminal ``results[rid]`` record with
  status ``shed`` and the gate's reason, and a requests.jsonl record —
  but never a queue slot, so admitted requests keep their p99.
  ``max_queue_depth`` is the hard backstop when no rate evidence exists
  yet.
- **Recovery from a failed decode dispatch** — an exception out of the
  decode/spec dispatch first retries through the shared
  ``guardrails/retry.py`` exponential backoff (transient faults heal
  in-place: nothing was mutated, the pools donate only on a successful
  dispatch entry). On exhaustion the manager **rebuilds in-process**:
  fresh BlockPool + paged device pools + prefix cache, decode jit caches
  dropped, and every live sequence **replayed** from its recorded
  prompt+generated tokens — a prefill over ``tokens[:-1]`` reconstructs
  KV ``[0, pos)`` exactly (the sampled token is discarded; under greedy
  it equals the already-recorded ``tokens[-1]``), warm-started through
  the fresh prefix cache as earlier replays populate it. A sequence that
  cannot replay (pool too tight) cold-requeues via the scheduler's
  always-correct preemption path. A fault that persists past the rebuild
  propagates loudly — recovery never loops.
- **Degradation ladder** — every anomaly (a recovery event, or a decode
  step slower than ``slow_step_ms``) feeds an escalating ladder, one rung
  per ``degrade_after`` anomalies: (1) speculative decoding off, (2)
  decode attention kernel → gather fallback, (3) admission batch cap
  halved (``Scheduler.slot_cap`` — no program recompile, capped slots are
  padding-masked like any idle slot). Rungs never un-climb within a
  process; the ``serving/degraded_level`` gauge is the operator's signal
  to rotate the replica.

Chaos comes from the same :class:`~deepspeed_tpu.resilience.fault.FaultPlan`
the training loop uses — ``serve_decode_fault_at_step`` /
``serve_slow_step_at_step`` (keyed on the engine's monotonic decode
dispatch-attempt counter, so retries consume the fault window) and
``serve_storm_at_step`` (a burst of duplicate submissions through the
normal ``submit`` path, i.e. through the shed gate). Injection is
independent of this manager: a fault with resilience OFF crashes the
serve loop — the motivating failure this module exists to absorb.

Every transition lands as ``serving/{shed_requests,deadline_expired,
cancelled,recoveries,retries,degraded_level}`` (emitted only when the
manager exists) and as a terminal ``status`` on the request record.
docs/SERVING.md "Serving under failure" is the operator story.
"""

import collections
import time
from typing import Any, Dict, List, Optional

from deepspeed_tpu.guardrails.retry import retry_call
from deepspeed_tpu.serving.scheduler import Request, Sequence
from deepspeed_tpu.utils.logging import logger

# Terminal statuses a request record can carry ("finished" is the happy
# path stamped by the engine itself).
TERMINAL_STATUSES = ("finished", "shed", "deadline_expired", "cancelled",
                     "aborted")


class ResilienceManager:
    """Per-engine serving resilience policy (docs/SERVING.md
    "Serving under failure").

    Host-side python only — admission math, deque surgery, counters.
    The single device-facing action is the rebuild path, which reuses
    the engine's own prefill programs to replay live sequences.
    """

    def __init__(self, engine):
        self.engine = engine
        self.cfg = engine.scfg
        self.counters: Dict[str, int] = {
            "shed_requests": 0, "deadline_expired": 0, "cancelled": 0,
            "recoveries": 0, "retries": 0,
        }
        self.degraded_level = 0
        self.anomalies = 0
        self._cancel_pending: set = set()

    # ------------------------------------------------------------------
    # admission control / load shedding
    # ------------------------------------------------------------------
    def _projected_wait_ms(self) -> Optional[float]:
        """Pending decode tokens over the measured decode rate: the
        rolling accountant window when the observatory is on (responsive
        under changing load), else the engine's cumulative token-weighted
        rate. None before any decode evidence — a cold engine never
        sheds on projection."""
        eng = self.engine
        rate = None
        if eng._req_acc is not None:
            rate = eng._req_acc.rolling_rate()
        if rate is None and eng._decode_sec > 0:
            rate = eng._decode_tokens / eng._decode_sec
        if not rate or rate <= 0:
            return None
        sched = eng.sched
        pending = sum(r.max_new_tokens for r in sched.waiting)
        pending += sum(
            max(0, s.request.max_new_tokens - s.generated)
            for s in sched.running.values())
        return pending / rate * 1e3

    def admission_gate(self, prompt: List[int],
                       max_new_tokens: int) -> Optional[str]:
        """Returns a shed reason, or None to admit to the queue."""
        depth = self.cfg.resil_max_queue_depth
        if depth is not None and self.engine.sched.queue_depth >= depth:
            return (f"queue depth {self.engine.sched.queue_depth} >= "
                    f"max_queue_depth {depth}")
        wait_ms = self.cfg.resil_max_queue_wait_ms
        if wait_ms is not None:
            projected = self._projected_wait_ms()
            if projected is not None and projected > wait_ms:
                return (f"projected queue wait {projected:.0f}ms > "
                        f"max_queue_wait_ms {wait_ms:.0f}ms")
        return None

    def shed(self, prompt: List[int], max_new_tokens: int,
             eos_token_id: Optional[int], reason: str) -> int:
        """Terminal-record a request WITHOUT queueing it. It still draws
        a real rid so every submission resolves through ``results``."""
        eng = self.engine
        rid = eng.sched.reserve_rid()
        req = Request(rid, list(prompt), int(max_new_tokens), eos_token_id)
        self.counters["shed_requests"] += 1
        eng.results[rid] = eng._queue_record(req, "shed", reason=reason)
        if eng._req_acc is not None:
            eng._req_acc.on_drop(req, "shed", eng._step_count)
        logger.warning("serving: shed request %d (%s)", rid, reason)
        return rid

    # ------------------------------------------------------------------
    # deadlines + cancellation (step-boundary resolution)
    # ------------------------------------------------------------------
    def request_cancel(self, rid: int) -> bool:
        eng = self.engine
        if rid in eng.results:
            return False
        known = any(r.rid == rid for r in eng.sched.waiting) or any(
            s.request.rid == rid for s in eng.sched.running.values())
        if not known:
            return False
        self._cancel_pending.add(rid)
        return True

    def process_boundary(self) -> None:
        """Resolve pending cancellations and expired deadlines — called
        once at the top of every ``step()``. Queue first (a queued drop
        never touches the pool), then running sequences (aborted with
        partial output; blocks released exactly once via
        ``Scheduler.abort``)."""
        eng = self.engine
        sched = eng.sched
        # A cancel that raced a natural finish is already terminal.
        self._cancel_pending -= set(eng.results)
        if not self._cancel_pending and not any(
                r.deadline is not None for r in sched.waiting) and not any(
                s.request.deadline is not None
                for s in sched.running.values()):
            return
        now = time.monotonic()
        if sched.waiting:
            keep: collections.deque = collections.deque()
            for req in sched.waiting:
                if req.rid in self._cancel_pending:
                    self._cancel_pending.discard(req.rid)
                    self._drop_queued(req, "cancelled")
                elif req.deadline is not None and now >= req.deadline:
                    self._drop_queued(req, "deadline_expired")
                else:
                    keep.append(req)
            sched.waiting = keep
        for seq in list(sched.running.values()):
            rid = seq.request.rid
            if rid in self._cancel_pending:
                self._cancel_pending.discard(rid)
                self._abort(seq, "cancelled")
            elif (seq.request.deadline is not None
                  and now >= seq.request.deadline):
                self._abort(seq, "deadline_expired")

    def _drop_queued(self, req: Request, status: str) -> None:
        eng = self.engine
        self.counters[status] += 1
        eng.results[req.rid] = eng._queue_record(req, status)
        if eng._req_acc is not None:
            eng._req_acc.on_drop(req, status, eng._step_count)

    def _abort(self, seq: Sequence, status: str) -> None:
        """Terminal-abort a RUNNING sequence: slot + KV blocks released
        exactly once (pool refcounts raise on a double release), partial
        output kept in the record."""
        eng = self.engine
        eng.sched.abort(seq)
        self.counters[status] += 1
        eng.results[seq.request.rid] = eng._result_record(seq, status)
        if eng._req_acc is not None:
            slo = eng._req_acc.on_finish(seq, eng._step_count,
                                         status=status)
            if slo is not None:
                eng.results[seq.request.rid]["slo"] = slo

    # ------------------------------------------------------------------
    # decode recovery + degradation ladder
    # ------------------------------------------------------------------
    def run_decode(self, active: List[Sequence], info: Dict[str, Any]):
        """The guarded decode round: dispatch, and on failure retry →
        rebuild+replay → one final unguarded dispatch (a persistent
        fault propagates loudly). Returns ``(n_tokens, dt_decode,
        active)`` — recovery can shrink the live set (cold requeues)."""
        eng = self.engine
        try:
            n_tokens, dt = eng._decode_round(active, info)
            return n_tokens, dt, active
        except Exception as e:  # noqa: BLE001 — the recovery entry point
            logger.warning("serving: decode dispatch failed (%s); "
                           "entering recovery", e)

        if self.cfg.resil_max_retries > 0:
            def _attempt():
                self.counters["retries"] += 1
                return eng._decode_round(active, info)

            try:
                n_tokens, dt = retry_call(
                    _attempt,
                    max_retries=self.cfg.resil_max_retries - 1,
                    base=self.cfg.resil_retry_base_sec, jitter=0.0,
                    retry_on=(Exception,),
                    describe="serving decode dispatch")
                self.note_anomaly()
                return n_tokens, dt, active
            except Exception:  # noqa: BLE001 — exhausted: rebuild next
                logger.warning(
                    "serving: decode retries exhausted (%d); rebuilding "
                    "decode state in-process",
                    self.cfg.resil_max_retries)

        self.counters["recoveries"] += 1
        self.note_anomaly()
        self._rebuild_and_replay()
        # Mirror the step boundary's capacity pass against the FRESH
        # block tables (a replay bucket may sit exactly at the next
        # write position), then dispatch unguarded.
        sched = eng.sched
        for seq in list(sched.active):
            if sched.running.get(seq.slot) is seq:
                sched.ensure_capacity(seq, lookahead=eng._spec_k)
        active = sched.active
        if not active:
            return 0, 0.0, active
        n_tokens, dt = eng._decode_round(active, info)
        return n_tokens, dt, active

    def note_step(self, dt_decode: float) -> None:
        """Slow-step anomaly: a decode dispatch past ``slow_step_ms``
        feeds the ladder (the straggler-step signal — on real pods a
        wedged core shows up exactly here)."""
        th = self.cfg.resil_slow_step_ms
        if th is not None and dt_decode * 1e3 > th:
            logger.warning("serving: slow decode step (%.1fms > %.1fms)",
                           dt_decode * 1e3, th)
            self.note_anomaly()

    def note_anomaly(self) -> None:
        self.anomalies += 1
        while (self.degraded_level < 3
               and self.anomalies >= self.cfg.resil_degrade_after
               * (self.degraded_level + 1)):
            self._escalate()

    def _escalate(self) -> None:
        """One ladder rung: trade throughput features for stability.
        Rungs never un-climb — a replica that had to degrade is a
        replica the operator should rotate, and flapping features back
        on under the same anomaly source would thrash."""
        eng = self.engine
        self.degraded_level += 1
        lvl = self.degraded_level
        if lvl == 1:
            eng._spec_k = 0
            if eng._req_acc is not None:
                eng._req_acc.spec_k = 0
            action = "speculative decoding off"
        elif lvl == 2:
            eng._attn_impl = "gather"
            eng._decode_jits.clear()
            eng._spec_jits.clear()
            action = "decode attention kernel -> gather"
        else:
            eng.sched.slot_cap = max(1, eng.scfg.max_batch_size // 2)
            action = (f"admission batch cap -> {eng.sched.slot_cap} "
                      f"slots")
        logger.warning("serving: degradation ladder -> level %d (%s) "
                       "after %d anomalies", lvl, action, self.anomalies)

    # ------------------------------------------------------------------
    # rebuild + replay
    # ------------------------------------------------------------------
    def _rebuild_and_replay(self) -> None:
        """Rebuild the KV substrate in-process and replay live
        sequences. The failed pool's device state is unrecoverable
        (donated buffers), so every block reference is dropped and a
        fresh BlockPool + paged pools + prefix cache replace it; decode
        jit caches are dropped (prefill programs are pure functions of
        their inputs and are kept). Sequences replay oldest-first so
        the fresh prefix cache warms later replays of a shared head."""
        from deepspeed_tpu.serving.kv_cache import BlockPool, \
            init_paged_pools
        from deepspeed_tpu.serving.scheduler import PrefixCache

        eng = self.engine
        sched = eng.sched
        live = sorted(sched.running.values(),
                      key=lambda s: (s.admitted_step, s.request.rid))
        for seq in live:
            seq.block_table = []
        pool = BlockPool(eng.scfg.kv_num_blocks)
        eng.pool = pool
        sched.pool = pool
        if eng.prefix_cache is not None:
            eng.prefix_cache = PrefixCache(pool, eng.block_size)
            sched.prefix_cache = eng.prefix_cache
        eng._pools = init_paged_pools(
            eng.model_cfg, eng.scfg.kv_num_blocks, eng.block_size,
            int8=eng.scfg.int8_kv_cache, dtype=eng._dtype)
        eng._decode_jits.clear()
        eng._spec_jits.clear()
        eng._mixed_jit = None   # donates the pools — old program's dead
        replayed = requeued = 0
        for seq in live:
            if self._replay(seq):
                replayed += 1
            else:
                # Cold requeue through the scheduler's always-correct
                # preemption path: restart from the prompt (greedy
                # decoding regenerates the same tokens).
                sched.preempt(seq)
                requeued += 1
        logger.warning(
            "serving: rebuilt KV pools + decode programs in-process "
            "(%d sequences replayed, %d requeued cold)",
            replayed, requeued)

    def _replay(self, seq: Sequence) -> bool:
        """Reconstruct ``seq``'s KV ``[0, pos)`` in the fresh pool by
        prefilling its recorded ``tokens[:-1]`` (prompt + generated so
        far, minus the last sampled token — whose KV was never written).
        Warm through the fresh prefix cache when the head matches an
        earlier replay. False → caller cold-requeues."""
        eng = self.engine
        sched = eng.sched
        replay = seq.tokens[:-1]
        if not replay or len(replay) > eng.bucket_cap:
            return False
        if eng._chunked and seq.prefilled < len(seq.request.prompt):
            # Mid-prefill chunked sequence: its prompt KV is only
            # partially written and it has sampled nothing, so a
            # tokens[:-1] replay can't express it. Cold requeue
            # restarts the prompt — always correct.
            return False
        bucket = eng._bucket_of(len(replay))
        shared: List[int] = []
        if sched.prefix_cache is not None:
            shared = sched.prefix_cache.match(replay, eng._step_count)
        n_shared = len(shared)
        blocks = eng.pool.alloc(bucket // eng.block_size - n_shared)
        if blocks is None:
            if shared:
                eng.pool.release(shared)
            return False
        if sched.prefix_cache is not None:
            sched.prefix_cache.commit_hit(n_shared)
        seq.bucket = bucket
        seq.block_table = shared + blocks
        seq.shared_len = n_shared * eng.block_size
        eng._replay_prefill(seq, replay)
        if sched.prefix_cache is not None:
            sched.prefix_cache.insert(replay, seq.block_table,
                                      eng._step_count)
        return True
