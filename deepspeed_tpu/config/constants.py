"""Config keys and defaults.

JSON key names deliberately match the reference (``deepspeed/runtime/constants.py``)
so that existing DeepSpeed config files parse unchanged; defaults are TPU-first
(bf16 preferred over fp16, no loss scaling needed for bf16).
"""

#############################################
# Batch size triple (reference constants.py)
#############################################
TRAIN_BATCH_SIZE = "train_batch_size"
TRAIN_MICRO_BATCH_SIZE_PER_GPU = "train_micro_batch_size_per_gpu"
# TPU-native alias accepted everywhere the reference key is.
TRAIN_MICRO_BATCH_SIZE_PER_CHIP = "train_micro_batch_size_per_chip"
GRADIENT_ACCUMULATION_STEPS = "gradient_accumulation_steps"

#############################################
# Optimizer / scheduler blocks
#############################################
OPTIMIZER = "optimizer"
OPTIMIZER_TYPE = "type"
OPTIMIZER_PARAMS = "params"
OPTIMIZER_TYPE_DEFAULT = None
# Fused blockwise Adam(W) update (ops/adam/fused_update.py): one Pallas
# pass over master + grad + moments per flat block instead of XLA's
# elementwise chain. Opt-in; requires a device-resident FusedAdam(W).
OPTIMIZER_FUSED_UPDATE = "fused_update"
OPTIMIZER_FUSED_UPDATE_DEFAULT = False
MAX_GRAD_NORM = "max_grad_norm"

SCHEDULER = "scheduler"
SCHEDULER_TYPE = "type"
SCHEDULER_PARAMS = "params"

# Optimizer names understood by the engine (reference engine.py:746-835).
ADAM_OPTIMIZER = "adam"
ADAMW_OPTIMIZER = "adamw"
LAMB_OPTIMIZER = "lamb"
ONEBIT_ADAM_OPTIMIZER = "onebitadam"
ONEBIT_LAMB_OPTIMIZER = "onebitlamb"
CPU_ADAM_OPTIMIZER = "cpuadam"  # host-offloaded update path
SGD_OPTIMIZER = "sgd"
DEEPSPEED_OPTIMIZERS = [
    ADAM_OPTIMIZER, ADAMW_OPTIMIZER, LAMB_OPTIMIZER, ONEBIT_ADAM_OPTIMIZER,
    ONEBIT_LAMB_OPTIMIZER, CPU_ADAM_OPTIMIZER, SGD_OPTIMIZER,
]

#############################################
# Precision (fp16 block kept for config parity; bf16 is TPU-native default)
#############################################
FP16 = "fp16"
FP16_ENABLED = "enabled"
FP16_LOSS_SCALE = "loss_scale"
FP16_INITIAL_SCALE_POWER = "initial_scale_power"
FP16_INITIAL_SCALE_POWER_DEFAULT = 32
FP16_LOSS_SCALE_WINDOW = "loss_scale_window"
FP16_LOSS_SCALE_WINDOW_DEFAULT = 1000
FP16_HYSTERESIS = "hysteresis"
FP16_HYSTERESIS_DEFAULT = 2
FP16_MIN_LOSS_SCALE = "min_loss_scale"
FP16_MIN_LOSS_SCALE_DEFAULT = 1.0

BF16 = "bf16"  # TPU-native block: {"enabled": true}
BFLOAT16 = "bfloat16"  # accepted alias
BF16_ENABLED = "enabled"

AMP = "amp"
AMP_ENABLED = "enabled"

GRADIENT_CLIPPING = "gradient_clipping"
GRADIENT_CLIPPING_DEFAULT = 0.0

PRESCALE_GRADIENTS = "prescale_gradients"
PRESCALE_GRADIENTS_DEFAULT = False
GRADIENT_PREDIVIDE_FACTOR = "gradient_predivide_factor"
GRADIENT_PREDIVIDE_FACTOR_DEFAULT = 1.0

#############################################
# Sparse gradients (embedding grads as COO/CSR — reference csr_tensor.py)
#############################################
SPARSE_GRADIENTS = "sparse_gradients"
SPARSE_GRADIENTS_DEFAULT = False

#############################################
# Resilience (TPU-native block, no reference analogue: preemption-aware
# async checkpointing + fault injection + auto-resume, resilience/)
#############################################
RESILIENCE = "resilience"
RESILIENCE_ENABLED = "enabled"
RESILIENCE_CHECKPOINT = "checkpoint"
RESILIENCE_CKPT_DIR = "dir"
RESILIENCE_CKPT_INTERVAL = "interval"
RESILIENCE_CKPT_INTERVAL_DEFAULT = 100
RESILIENCE_CKPT_KEEP_LAST = "keep_last"
RESILIENCE_CKPT_KEEP_LAST_DEFAULT = 3
RESILIENCE_CKPT_MAX_RETRIES = "max_retries"
RESILIENCE_CKPT_MAX_RETRIES_DEFAULT = 3
RESILIENCE_CKPT_BACKOFF = "backoff_seconds"
RESILIENCE_CKPT_BACKOFF_DEFAULT = 0.5
RESILIENCE_CKPT_ASYNC = "async"
RESILIENCE_CKPT_ASYNC_DEFAULT = True
RESILIENCE_AUTO_RESUME = "auto_resume"
RESILIENCE_AUTO_RESUME_DEFAULT = True
RESILIENCE_FAULT_INJECTION = "fault_injection"

#############################################
# Guardrails (TPU-native block, no reference analogue beyond the fp16
# CheckOverflow path: anomaly detection + in-memory rollback + step
# watchdog, guardrails/; docs/RESILIENCE.md "Guardrails")
#############################################
GUARDRAILS = "guardrails"
GUARDRAILS_ENABLED = "enabled"
GUARDRAILS_DETECTOR = "detector"
GUARDRAILS_DET_ZSCORE = "zscore_threshold"
GUARDRAILS_DET_ZSCORE_DEFAULT = 6.0
GUARDRAILS_DET_WARMUP = "warmup_steps"
GUARDRAILS_DET_WARMUP_DEFAULT = 20
GUARDRAILS_DET_EWMA_ALPHA = "ewma_alpha"
GUARDRAILS_DET_EWMA_ALPHA_DEFAULT = 0.02
GUARDRAILS_DET_TRACK_GRAD_NORM = "track_grad_norm"
GUARDRAILS_DET_TRACK_GRAD_NORM_DEFAULT = True
GUARDRAILS_DET_NONFINITE_GRADS = "check_nonfinite_grads"
GUARDRAILS_DET_NONFINITE_GRADS_DEFAULT = False
GUARDRAILS_ROLLBACK = "rollback"
GUARDRAILS_RB_ENABLED = "enabled"
GUARDRAILS_RB_ENABLED_DEFAULT = True
GUARDRAILS_RB_SNAPSHOT_INTERVAL = "snapshot_interval"
GUARDRAILS_RB_SNAPSHOT_INTERVAL_DEFAULT = 10
GUARDRAILS_RB_RING_SIZE = "ring_size"
GUARDRAILS_RB_RING_SIZE_DEFAULT = 2
GUARDRAILS_RB_CONSECUTIVE_SPIKES = "consecutive_spikes"
GUARDRAILS_RB_CONSECUTIVE_SPIKES_DEFAULT = 2
GUARDRAILS_RB_SKIP_BATCHES = "skip_batches"
GUARDRAILS_RB_SKIP_BATCHES_DEFAULT = 2
GUARDRAILS_RB_LR_DECAY = "lr_decay"
GUARDRAILS_RB_LR_DECAY_DEFAULT = 1.0
GUARDRAILS_RB_MAX_ROLLBACKS = "max_rollbacks"
GUARDRAILS_RB_MAX_ROLLBACKS_DEFAULT = 3
GUARDRAILS_RB_ESCALATE = "escalate_to_disk"
GUARDRAILS_RB_ESCALATE_DEFAULT = True
GUARDRAILS_WATCHDOG = "watchdog"
GUARDRAILS_WD_ENABLED = "enabled"
GUARDRAILS_WD_ENABLED_DEFAULT = False
GUARDRAILS_WD_TIMEOUT = "step_timeout_seconds"
GUARDRAILS_WD_TIMEOUT_DEFAULT = 1800.0
GUARDRAILS_WD_POLL = "poll_interval_seconds"
GUARDRAILS_WD_CRASHDUMP_DIR = "crashdump_dir"
GUARDRAILS_WD_CRASHDUMP_DIR_DEFAULT = "crashdumps"
GUARDRAILS_WD_EXIT_CODE = "exit_code"
# Distinct from everything the runtime otherwise produces (1 generic, 2
# pytest/usage, 137/139/143 signal deaths): the supervisor maps THIS rc to
# an immediate no-backoff restart — a hang already burned its budget.
GUARDRAILS_WATCHDOG_EXIT_CODE_DEFAULT = 113

#############################################
# Telemetry (TPU-native block, no reference analogue: unified metrics
# registry + step tracer + recompilation detector, telemetry/)
#############################################
TELEMETRY = "telemetry"
TELEMETRY_ENABLED = "enabled"
TELEMETRY_DIR = "dir"
TELEMETRY_DIR_DEFAULT = "telemetry"
TELEMETRY_TRACE = "trace"
TELEMETRY_TRACE_ENABLED = "enabled"
TELEMETRY_TRACE_ENABLED_DEFAULT = True
TELEMETRY_TRACE_FILE = "file"
TELEMETRY_TRACE_FILE_DEFAULT = "trace.json"
TELEMETRY_TRACE_SYNC_SPANS = "sync_spans"
TELEMETRY_TRACE_SYNC_SPANS_DEFAULT = True
TELEMETRY_TRACE_JAX_PROFILER_DIR = "jax_profiler_dir"
TELEMETRY_METRICS = "metrics"
TELEMETRY_METRICS_SINKS = "sinks"
TELEMETRY_METRICS_SINKS_DEFAULT = ("jsonl",)
TELEMETRY_METRICS_VALID_SINKS = ("jsonl", "tensorboard", "memory")
TELEMETRY_METRICS_FILE = "file"
TELEMETRY_METRICS_FILE_DEFAULT = "metrics.jsonl"
TELEMETRY_RECOMPILE = "recompile_detection"
TELEMETRY_RECOMPILE_DEFAULT = True
# Goodput accounting (telemetry/goodput.py): run-level wall-clock
# attribution + MFU + per-attempt run manifests. Rides the telemetry
# block; default ON when telemetry is enabled (it adds zero device syncs
# — pure host clock reads).
TELEMETRY_GOODPUT = "goodput"
TELEMETRY_GOODPUT_DEFAULT = True
# Fleet observability (telemetry/fleet.py): cross-host metric aggregation
# at flush boundaries (a tiny jitted all-gather OFF the step path) +
# rolling-window straggler detection. Default OFF: enabled it adds one
# collective + one host fetch per flush, which the zero-overhead contract
# reserves for explicit opt-in.
TELEMETRY_FLEET = "fleet"
TELEMETRY_FLEET_ENABLED = "enabled"
TELEMETRY_FLEET_ENABLED_DEFAULT = False
TELEMETRY_FLEET_WINDOW = "window"
TELEMETRY_FLEET_WINDOW_DEFAULT = 8            # flushes in the z-score window
TELEMETRY_FLEET_MIN_WINDOW = "min_window"
TELEMETRY_FLEET_MIN_WINDOW_DEFAULT = 3        # flushes before verdicts fire
TELEMETRY_FLEET_ZSCORE = "zscore"
TELEMETRY_FLEET_ZSCORE_DEFAULT = 3.0
TELEMETRY_FLEET_PERSIST = "persist"
TELEMETRY_FLEET_PERSIST_DEFAULT = 3           # verdicts until "persistent"
TELEMETRY_FLEET_BREAKDOWN_FILE = "breakdown_file"
TELEMETRY_FLEET_BREAKDOWN_FILE_DEFAULT = "fleet_breakdown.json"
# Memory observatory (telemetry/memory.py): XLA memory attribution +
# model-state ledger + capacity planner + OOM forensics. Default OFF:
# enabled it adds one AOT lower+compile per step function (attribution)
# and per-step headroom gauges — reserved for explicit opt-in like fleet.
TELEMETRY_MEMORY = "memory"
TELEMETRY_MEMORY_ENABLED = "enabled"
TELEMETRY_MEMORY_ENABLED_DEFAULT = False
TELEMETRY_MEMORY_HEADROOM_WARN_FRAC = "headroom_warn_frac"
TELEMETRY_MEMORY_HEADROOM_WARN_FRAC_DEFAULT = 0.1   # warn below 10% of HBM
TELEMETRY_MEMORY_CRASHDUMP_DIR = "crashdump_dir"
TELEMETRY_MEMORY_CRASHDUMP_DIR_DEFAULT = "crashdumps"
TELEMETRY_MEMORY_OOM_EXIT_CODE = "oom_exit_code"
TELEMETRY_MEMORY_PLAN_AT_INIT = "plan_at_init"
TELEMETRY_MEMORY_PLAN_AT_INIT_DEFAULT = True
TELEMETRY_MEMORY_PLAN_FILE = "plan_file"
TELEMETRY_MEMORY_PLAN_FILE_DEFAULT = "memory_plan.json"
TELEMETRY_MEMORY_ACT_BYTES = "activation_bytes_per_sample"
TELEMETRY_MEMORY_ACT_BYTES_DEFAULT = 0.0
TELEMETRY_MEMORY_HBM_LIMIT_GB = "hbm_limit_gb"
# Distinct from rc 113 (watchdog: immediate restart) by design: the
# supervisor maps THIS rc to cause=oom and does NOT restart at all — a
# deterministic OOM is a config bug, and a hot restart loop would just
# re-OOM until the budget is gone.
MEMORY_OOM_EXIT_CODE_DEFAULT = 114
# Device-time observatory (telemetry/devicetime.py): scheduled
# jax.profiler captures parsed into measured op-level attribution,
# roofline classification and measured exposed-comm. Default OFF:
# enabled it adds profiler start/stop + one device drain + a parse at
# capture boundaries (never on the in-between step path) — explicit
# opt-in like fleet/memory.
TELEMETRY_DEVICETIME = "devicetime"
TELEMETRY_DEVICETIME_ENABLED = "enabled"
TELEMETRY_DEVICETIME_ENABLED_DEFAULT = False
TELEMETRY_DEVICETIME_CAPTURE_STEPS = "capture_steps"
TELEMETRY_DEVICETIME_CAPTURE_STEPS_DEFAULT = 3    # steps per capture
TELEMETRY_DEVICETIME_EVERY_STEPS = "every_steps"
TELEMETRY_DEVICETIME_EVERY_STEPS_DEFAULT = 200    # capture cadence
TELEMETRY_DEVICETIME_KEEP_LAST = "keep_last"
TELEMETRY_DEVICETIME_KEEP_LAST_DEFAULT = 2        # capture-dir GC
TELEMETRY_DEVICETIME_DIR = "dir"
TELEMETRY_DEVICETIME_DIR_DEFAULT = "devicetime"   # under telemetry.dir
TELEMETRY_DEVICETIME_TOP_K = "top_k"
TELEMETRY_DEVICETIME_TOP_K_DEFAULT = 10           # hottest-op table rows
TELEMETRY_DEVICETIME_DIVERGENCE_WARN = "divergence_warn"
TELEMETRY_DEVICETIME_DIVERGENCE_WARN_DEFAULT = 0.25  # |measured-modeled|
TELEMETRY_DEVICETIME_HBM_GBPS = "hbm_gbps"        # None -> per-kind table
# Numerics observatory (telemetry/numerics.py): per-layer-group
# gradient/update statistics + dtype-saturation counters computed INSIDE
# the jitted step (one small stacked aux array, fetched once per flush),
# and quantization-error attribution for the int8 wire paths. Default
# OFF: enabled it adds the in-program stat reductions to the step
# program (the lowered step changes — explicit opt-in, unlike the
# jaxpr-neutral memory observatory) and one host transfer per flush.
TELEMETRY_NUMERICS = "numerics"
TELEMETRY_NUMERICS_ENABLED = "enabled"
TELEMETRY_NUMERICS_ENABLED_DEFAULT = False
TELEMETRY_NUMERICS_MAX_GROUPS = "max_groups"
TELEMETRY_NUMERICS_MAX_GROUPS_DEFAULT = 16        # top-level key cap
TELEMETRY_NUMERICS_MAX_SPIKE_DUMPS = "max_spike_dumps"
TELEMETRY_NUMERICS_MAX_SPIKE_DUMPS_DEFAULT = 8    # per-run dump budget
# Request observatory (telemetry/requests.py): per-request SLO
# accounting for the serve engine — exact lifetime partition, TPOT/e2e
# histograms, host-scoped requests.<host>.jsonl records, an engine-side
# serving-time partition, and the rolling decode-throughput window.
# Default OFF: enabled it adds host float arithmetic per step (no device
# syncs) plus one JSONL append per finished request — explicit opt-in
# like fleet/memory, and the off state keeps the engine's emitted tag
# set byte-identical.
TELEMETRY_REQUESTS = "requests"
TELEMETRY_REQUESTS_ENABLED = "enabled"
TELEMETRY_REQUESTS_ENABLED_DEFAULT = False
TELEMETRY_REQUESTS_FILE = "file"
TELEMETRY_REQUESTS_FILE_DEFAULT = "requests.jsonl"
TELEMETRY_REQUESTS_WINDOW_SEC = "window_sec"
TELEMETRY_REQUESTS_WINDOW_SEC_DEFAULT = 10.0  # rolling-throughput window

#############################################
# Serving (TPU-native block, no reference analogue: continuous-batching
# serving engine over the inference stack — serving/; docs/SERVING.md)
#############################################
SERVING = "serving"
SERVING_MAX_BATCH_SIZE = "max_batch_size"
SERVING_MAX_BATCH_SIZE_DEFAULT = 8            # decode slots
SERVING_KV_BLOCK_SIZE = "kv_block_size"
SERVING_KV_BLOCK_SIZE_DEFAULT = 16            # cache positions per block
SERVING_KV_NUM_BLOCKS = "kv_num_blocks"
SERVING_KV_NUM_BLOCKS_DEFAULT = 256           # pool size (block 0 = scratch)
SERVING_INT8_KV_CACHE = "int8_kv_cache"
SERVING_INT8_KV_CACHE_DEFAULT = False         # blockwise-int8 KV pools
SERVING_MAX_MODEL_LEN = "max_model_len"       # None -> model max_seq_len
SERVING_MAX_PREFILLS_PER_STEP = "max_prefills_per_step"
SERVING_MAX_PREFILLS_PER_STEP_DEFAULT = 1     # prefill/decode interleave cap
SERVING_EOS_TOKEN_ID = "eos_token_id"         # None -> length-only stopping
SERVING_TEMPERATURE = "temperature"
SERVING_TEMPERATURE_DEFAULT = 0.0             # greedy
SERVING_TOP_K = "top_k"
SERVING_TOP_K_DEFAULT = 0
SERVING_SEED = "seed"
SERVING_SEED_DEFAULT = 0
# decode fast path (docs/SERVING.md "Decode fast path"): "gather" keeps
# the PR-8 full-window gather program bit-identical; "auto" runs the
# Pallas paged decode-attention kernel where the geometry tiles and the
# max-active-length-capped gather elsewhere; "kernel" forces the kernel
# (Pallas interpreter off-TPU — the parity/bench path).
SERVING_DECODE_ATTENTION = "decode_attention"
SERVING_DECODE_ATTENTION_DEFAULT = "gather"
SERVING_DECODE_ATTENTION_CHOICES = ("gather", "auto", "kernel")
# prefix-cache reuse: ref-counted prompt-head trie over KV blocks —
# warm heads skip the shared portion of prefill (COW adoption).
SERVING_PREFIX_CACHE = "prefix_cache"
SERVING_PREFIX_CACHE_DEFAULT = False
# speculative decoding sub-block
SERVING_SPECULATIVE = "speculative"
SERVING_SPEC_ENABLED = "enabled"
SERVING_SPEC_ENABLED_DEFAULT = False
SERVING_SPEC_K = "k"                      # draft tokens proposed per round
SERVING_SPEC_K_DEFAULT = 4
SERVING_SPEC_DRAFT_LAYERS = "draft_layers"  # None -> num_layers // 2
# serving resilience sub-block (serving/resilience.py; docs/SERVING.md
# "Serving under failure"): deadlines + cancellation, SLO-aware load
# shedding, in-flight recovery + degradation ladder — off by default
# under the established zero-overhead contract.
SERVING_RESILIENCE = "resilience"
SERVING_RESIL_ENABLED = "enabled"
SERVING_RESIL_ENABLED_DEFAULT = False
SERVING_RESIL_MAX_QUEUE_DEPTH = "max_queue_depth"      # None -> unbounded
SERVING_RESIL_MAX_QUEUE_WAIT_MS = "max_queue_wait_ms"  # None -> no wait gate
SERVING_RESIL_DEFAULT_DEADLINE_MS = "default_deadline_ms"  # None -> none
SERVING_RESIL_MAX_RETRIES = "max_retries"  # decode-dispatch retries
SERVING_RESIL_MAX_RETRIES_DEFAULT = 2
SERVING_RESIL_RETRY_BASE_SEC = "retry_base_sec"
SERVING_RESIL_RETRY_BASE_SEC_DEFAULT = 0.05
SERVING_RESIL_DEGRADE_AFTER = "degrade_after"  # anomalies per ladder rung
SERVING_RESIL_DEGRADE_AFTER_DEFAULT = 2
SERVING_RESIL_SLOW_STEP_MS = "slow_step_ms"  # None -> no slow-step anomaly
# chunked-prefill sub-block (ops/transformer/chunked_prefill.py;
# docs/SERVING.md "Chunked prefill admission"): Sarathi-style mixed
# decode + prefill-chunk steps through ONE ragged program — off by
# default under the established zero-overhead contract.
SERVING_CHUNKED_PREFILL = "chunked_prefill"
SERVING_CHUNKED_ENABLED = "enabled"
SERVING_CHUNKED_ENABLED_DEFAULT = False
SERVING_CHUNKED_TOKEN_BUDGET = "token_budget"  # tokens per mixed step
SERVING_CHUNKED_TOKEN_BUDGET_DEFAULT = 64

#############################################
# Logging / misc
#############################################
STEPS_PER_PRINT = "steps_per_print"
STEPS_PER_PRINT_DEFAULT = 10
WALL_CLOCK_BREAKDOWN = "wall_clock_breakdown"
WALL_CLOCK_BREAKDOWN_DEFAULT = False
DUMP_STATE = "dump_state"
DUMP_STATE_DEFAULT = False
CHECK_NUMERICS = "check_numerics"
CHECK_NUMERICS_DEFAULT = False
MEMORY_BREAKDOWN = "memory_breakdown"
MEMORY_BREAKDOWN_DEFAULT = False

TENSORBOARD = "tensorboard"
TENSORBOARD_ENABLED = "enabled"
TENSORBOARD_OUTPUT_PATH = "output_path"
TENSORBOARD_JOB_NAME = "job_name"

#############################################
# ZeRO (full key set in runtime/zero/config.py)
#############################################
ZERO_OPTIMIZATION = "zero_optimization"

#############################################
# Activation checkpointing
#############################################
ACTIVATION_CHECKPOINTING = "activation_checkpointing"
ACT_CHKPT_PARTITION_ACTIVATIONS = "partition_activations"
ACT_CHKPT_NUMBER_CHECKPOINTS = "number_checkpoints"
ACT_CHKPT_CONTIGUOUS_MEMORY_OPTIMIZATION = "contiguous_memory_optimization"
ACT_CHKPT_SYNCHRONIZE_CHECKPOINT_BOUNDARY = "synchronize_checkpoint_boundary"
ACT_CHKPT_PROFILE = "profile"
ACT_CHKPT_CPU_CHECKPOINTING = "cpu_checkpointing"

#############################################
# Pipeline block (reference config.py:409)
#############################################
PIPELINE = "pipeline"
PIPELINE_STAGES = "stages"
PIPELINE_PARTITION = "partition"
PIPELINE_SEED_LAYERS = "seed_layers"
PIPELINE_ACTIVATION_CHECKPOINT_INTERVAL = "activation_checkpoint_interval"

#############################################
# Sparse attention presets (reference config.py:261-407)
#############################################
SPARSE_ATTENTION = "sparse_attention"
SPARSE_MODE = "mode"
SPARSE_DENSE_MODE = "dense"
SPARSE_FIXED_MODE = "fixed"
SPARSE_VARIABLE_MODE = "variable"
SPARSE_BIGBIRD_MODE = "bigbird"
SPARSE_BSLONGFORMER_MODE = "bslongformer"

#############################################
# Flops profiler
#############################################
FLOPS_PROFILER = "flops_profiler"
FLOPS_PROFILER_ENABLED = "enabled"
FLOPS_PROFILER_PROFILE_STEP = "profile_step"
FLOPS_PROFILER_MODULE_DEPTH = "module_depth"
FLOPS_PROFILER_TOP_MODULES = "top_modules"
FLOPS_PROFILER_DETAILED = "detailed"
FLOPS_PROFILER_OUTPUT_FILE = "output_file"

#############################################
# Progressive layer drop / eigenvalue / MoQ
#############################################
PROGRESSIVE_LAYER_DROP = "progressive_layer_drop"
PLD_ENABLED = "enabled"
PLD_THETA = "theta"
PLD_GAMMA = "gamma"

EIGENVALUE = "eigenvalue"
QUANTIZE_TRAINING = "quantize_training"

#############################################
# Elasticity
#############################################
ELASTICITY = "elasticity"
# Live elasticity (resilience/elastic.py; docs/RESILIENCE.md "Live
# elasticity"): in-process shrink on a preemption advance warning,
# step-boundary rejoin, and goodput-driven straggler eviction. Rides the
# elasticity block (`elasticity.live`); default OFF — disabled means no
# signal handlers, zero extra syncs, bit-identical lowered step.
ELASTICITY_LIVE = "live"
ELASTICITY_LIVE_ENABLED = "enabled"
ELASTICITY_LIVE_ENABLED_DEFAULT = False
# Preemption advance-warning grace window: the platform sends SIGTERM
# this many seconds before pulling the slice; the coordinator must drain
# + reshard inside it (GCE preemptible TPUs give 30s; tests use less).
ELASTICITY_LIVE_GRACE = "grace_seconds"
ELASTICITY_LIVE_GRACE_DEFAULT = 30.0
# Step cadence at which the coordinator polls the rejoin rendezvous file
# (one os.path check per poll — rejoin admission happens at the next
# snapshot boundary, not mid-step).
ELASTICITY_LIVE_CHECK_INTERVAL = "check_interval_steps"
ELASTICITY_LIVE_CHECK_INTERVAL_DEFAULT = 10
# Straggler eviction (the PR-6 Supervisor.straggler_hosts loop closed):
# a persistent straggler is evicted only when the goodput cost model says
# projected_gain = straggler_sec_rate x horizon_steps exceeds
# min_gain_factor x measured reshard cost.
ELASTICITY_LIVE_EVICTION = "eviction"
ELASTICITY_LIVE_EVICTION_ENABLED = "enabled"
ELASTICITY_LIVE_EVICTION_ENABLED_DEFAULT = False
ELASTICITY_LIVE_EVICTION_HORIZON = "horizon_steps"
ELASTICITY_LIVE_EVICTION_HORIZON_DEFAULT = 1000
ELASTICITY_LIVE_EVICTION_MIN_GAIN = "min_gain_factor"
ELASTICITY_LIVE_EVICTION_MIN_GAIN_DEFAULT = 2.0
# Reshard cost assumed before the first measured in-process reshard
# (afterwards the measured elastic/reshard_sec wins).
ELASTICITY_LIVE_EVICTION_ASSUMED_RESHARD = "assumed_reshard_sec"
ELASTICITY_LIVE_EVICTION_ASSUMED_RESHARD_DEFAULT = 60.0
# Exit code when the coordinator received the advance warning but could
# not stay up (no surviving capacity / no valid elastic world): the
# supervisor classifies it `preemption_warned` — distinct from rc -15
# (plain preemption: the process died without handling the warning).
# Distinct from 113 (watchdog) and 114 (oom) by design.
ELASTICITY_LIVE_EXIT_CODE = "exit_code"
ELASTIC_PREEMPT_EXIT_CODE_DEFAULT = 115

#############################################
# Offload / async IO
#############################################
AIO = "aio"
AIO_BLOCK_SIZE = "block_size"
AIO_BLOCK_SIZE_DEFAULT = 1048576
AIO_QUEUE_DEPTH = "queue_depth"
AIO_QUEUE_DEPTH_DEFAULT = 8
AIO_THREAD_COUNT = "thread_count"
AIO_THREAD_COUNT_DEFAULT = 1
AIO_SINGLE_SUBMIT = "single_submit"
AIO_SINGLE_SUBMIT_DEFAULT = False
AIO_OVERLAP_EVENTS = "overlap_events"
AIO_OVERLAP_EVENTS_DEFAULT = True

#############################################
# Mesh / parallelism (TPU-native block, no reference analogue:
# the reference takes TP degree from the external mpu object)
#############################################
MESH = "mesh"
MESH_DATA = "data"
MESH_MODEL = "model"
MESH_PIPE = "pipe"
MESH_SEQUENCE = "sequence"
MESH_EXPERT = "expert"
MESH_SLICES = "slices"

#############################################
# Communication / compression
#############################################
COMMUNICATION_DATA_TYPE = "communication_data_type"
COMPRESSED_ALLREDUCE = "compressed_allreduce"

# comm block — hierarchical quantized gradient sync (comm/grad_sync.py):
# bucketed ICI reduce-scatter + blockwise-quantized DCN all-reduce.
COMM = "comm"
COMM_HIERARCHICAL = "hierarchical"
# Default OFF: the implicit pjit path stays bit-identical unless the user
# opts in ("auto" engages on multi-slice meshes, "on" forces).
COMM_HIERARCHICAL_DEFAULT = "off"             # auto | on | off
COMM_DCN_QUANT_BITS = "dcn_quant_bits"
COMM_DCN_QUANT_BITS_DEFAULT = 8               # 8=int8, 16=bf16, 32=fp32
COMM_QUANT_BLOCK_SIZE = "quant_block_size"
COMM_QUANT_BLOCK_SIZE_DEFAULT = 1024
COMM_BUCKET_MB = "bucket_mb"
COMM_BUCKET_MB_DEFAULT = 16.0
# Overlapped gradient sync (docs/PERFORMANCE.md "Overlapped gradient
# sync"): readiness-ordered per-bucket ICI reduce-scatter during
# backward + double-buffered per-microstep DCN all-reduce. "auto"
# (default) engages whenever the hierarchical sync does; "off" keeps
# the PR-4 GAS-boundary schedule.
COMM_OVERLAP_GRAD_SYNC = "overlap_grad_sync"
COMM_OVERLAP_GRAD_SYNC_DEFAULT = "auto"       # auto | on | off
# Nominal per-device link bandwidths behind the modeled device-time
# attribution (comm/exposed_frac): exposed-collective seconds =
# bytes_dcn / dcn + bytes_ici / ici. Defaults approximate a v4-class
# slice (ICI ~90 GB/s per chip) and a 100 Gbit/s DCN NIC per host;
# override per deployment for honest fractions.
COMM_ICI_GBPS = "ici_gbps"
COMM_ICI_GBPS_DEFAULT = 90.0
COMM_DCN_GBPS = "dcn_gbps"
COMM_DCN_GBPS_DEFAULT = 12.5

# ZeRO++ weight path: zero_optimization.zeropp — runtime/zero/config.py
# ZeroPPConfig owns the keys/defaults (they live beside the other
# zero_optimization key constants); the param-hop comm gauge names are
# declared in comm/grad_sync.py COMM_PARAM_METRIC_TAGS, doc-lint-pinned.

#############################################
# Autotuning (autotuning/; docs/PERFORMANCE.md "Autotuning"): startup
# config search — enumerate the knob space, prune against the ConfigError
# walls and the capacity projection, rank survivors with the modeled cost
# model, measure the top-K with short in-process trials, adopt the
# fastest. Default OFF: no autotuning import at engine init, zero extra
# syncs, bit-identical lowered step (tests/test_autotuning.py pins it).
#############################################
AUTOTUNING = "autotuning"
AUTOTUNING_ENABLED = "enabled"
AUTOTUNING_ENABLED_DEFAULT = False
# Launcher handshake: `dstpu --autotune ...` exports this env for every
# child so unmodified training scripts pick the search up through their
# config parse (the script still owes the tuner a batch source — see
# deepspeed_tpu.autotune / initialize(autotune_batches=...)).
AUTOTUNING_ENV = "DSTPU_AUTOTUNE"
# Knob-space overrides. Empty tuples mean "derive from the runtime
# shape": stages 0-3, the elastic ladder's (micro, gas) splits (divisor
# re-splits of the configured product when elasticity is off), and the
# comm/zeropp axes only where the mesh gives them meaning (dcn > 1).
AUTOTUNING_ZERO_STAGES = "zero_stages"
AUTOTUNING_MICRO_GAS = "micro_gas"               # [[micro, gas], ...]
AUTOTUNING_BUCKET_MBS = "bucket_mbs"
AUTOTUNING_DCN_QUANT_BITS = "dcn_quant_bits"
AUTOTUNING_OVERLAP = "overlap"                   # overlap_grad_sync values
AUTOTUNING_ZEROPP = "zeropp"                     # "off" | "bf16" | "int8"
# Measured-trial knobs: top_k survivors get compile + trial_steps timed
# steps; successive halving drops candidates slower than
# halving_factor x the round's best before the confirmation round.
AUTOTUNING_TOP_K = "top_k"
AUTOTUNING_TOP_K_DEFAULT = 3
AUTOTUNING_TRIAL_STEPS = "trial_steps"
AUTOTUNING_TRIAL_STEPS_DEFAULT = 3
AUTOTUNING_TRIAL_WARMUP = "trial_warmup"
AUTOTUNING_TRIAL_WARMUP_DEFAULT = 1
AUTOTUNING_HALVING_FACTOR = "halving_factor"
AUTOTUNING_HALVING_FACTOR_DEFAULT = 1.5
# Capacity wall: a candidate whose projected device bytes exceed
# headroom_frac x the HBM limit is pruned before any trial (the
# projection is telemetry/memory.py plan_capacity, engine-free).
AUTOTUNING_HEADROOM_FRAC = "headroom_frac"
AUTOTUNING_HEADROOM_FRAC_DEFAULT = 0.9
AUTOTUNING_ACT_BYTES = "activation_bytes_per_sample"
AUTOTUNING_ACT_BYTES_DEFAULT = 0.0
AUTOTUNING_HBM_LIMIT_GB = "hbm_limit_gb"         # None -> device limit
AUTOTUNING_MAX_CANDIDATES = "max_candidates"
AUTOTUNING_MAX_CANDIDATES_DEFAULT = 64
AUTOTUNING_RESULT_FILE = "result_file"
AUTOTUNING_RESULT_FILE_DEFAULT = "autotune_result.json"
# MoE axes (active only when the moe block is enabled; collapsed with a
# note otherwise). capacity_factor and dispatch are trial-safe —
# lowering-only changes. num_experts re-shapes the expert params, so
# candidates that change it are enumerated (the config-parse walls prune
# invalid counts for free) but never measured in-process: the trial
# rebuild reinstalls the pre-search parameter snapshot, which an
# expert-count change cannot fit.
AUTOTUNING_MOE_EXPERTS = "moe_experts"
AUTOTUNING_MOE_CAPACITY_FACTORS = "moe_capacity_factors"
AUTOTUNING_MOE_DISPATCH = "moe_dispatch"

#############################################
# MoE / expert parallelism (moe/; docs/MOE.md): the GShard-style MoE FFN
# swap for the in-tree GPT family plus the explicit all-to-all dispatch
# path. Default ABSENT: no moe block => initialize() performs no model
# surgery and the lowered train step is bit-identical (tests/test_moe.py
# pins it). The moe/* gauge names are declared in telemetry/moe.py
# MOE_METRIC_TAGS, doc-lint-pinned like numerics/goodput.
#############################################
MOE = "moe"
MOE_ENABLED = "enabled"
MOE_ENABLED_DEFAULT = False
MOE_NUM_EXPERTS = "num_experts"
MOE_NUM_EXPERTS_DEFAULT = 8
MOE_TOP_K = "k"                                # top-k routing (1 or 2)
MOE_TOP_K_DEFAULT = 1
MOE_LAYER_FREQ = "layer_freq"                  # every Nth block is MoE
MOE_LAYER_FREQ_DEFAULT = 2
MOE_CAPACITY_FACTOR = "capacity_factor"
MOE_CAPACITY_FACTOR_DEFAULT = 1.25
MOE_EVAL_CAPACITY_FACTOR = "eval_capacity_factor"
MOE_EVAL_CAPACITY_FACTOR_DEFAULT = 2.0
MOE_MIN_CAPACITY = "min_capacity"
MOE_MIN_CAPACITY_DEFAULT = 4
MOE_AUX_ALPHA = "aux_alpha"                    # load-balance loss scale
MOE_AUX_ALPHA_DEFAULT = 0.01
MOE_ROUTER_JITTER = "router_jitter"            # train-only input jitter
MOE_ROUTER_JITTER_DEFAULT = 0.0
MOE_DISPATCH = "dispatch"
MOE_DISPATCH_DEFAULT = "scatter"
MOE_DISPATCH_CHOICES = ("einsum", "scatter", "alltoall")
