"""Top-level config system.

Parity with the reference ``DeepSpeedConfig`` (``deepspeed/runtime/config.py:655``):
one JSON document (path or dict) parsed into typed sub-configs, including the
three-way batch-size constraint solver
``train_batch_size = micro_batch_per_device × gradient_accumulation_steps × dp_world_size``
(reference ``config.py:822-893``).

TPU-first deltas:
- a ``bf16`` block is first-class and is the preferred precision (no loss
  scaling required); ``fp16`` is kept for config-compat and engages the
  dynamic loss scaler.
- a ``mesh`` block declares named parallel axes (data/model/pipe/sequence/
  expert) — the reference delegated TP shape to an external Megatron ``mpu``.
"""

import json
import os
from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Union

from deepspeed_tpu.config import constants as C
from deepspeed_tpu.runtime.zero.config import ZeroConfig


class ConfigError(ValueError):
    pass


def _get(d: Dict[str, Any], key: str, default: Any) -> Any:
    v = d.get(key, default)
    return default if v is None else v


@dataclass
class FP16Config:
    enabled: bool = False
    loss_scale: float = 0.0  # 0 => dynamic
    initial_scale_power: int = C.FP16_INITIAL_SCALE_POWER_DEFAULT
    loss_scale_window: int = C.FP16_LOSS_SCALE_WINDOW_DEFAULT
    hysteresis: int = C.FP16_HYSTERESIS_DEFAULT
    min_loss_scale: float = C.FP16_MIN_LOSS_SCALE_DEFAULT

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> "FP16Config":
        d = d or {}
        return cls(
            enabled=bool(_get(d, C.FP16_ENABLED, False)),
            loss_scale=float(_get(d, C.FP16_LOSS_SCALE, 0.0)),
            initial_scale_power=int(_get(d, C.FP16_INITIAL_SCALE_POWER,
                                         C.FP16_INITIAL_SCALE_POWER_DEFAULT)),
            loss_scale_window=int(_get(d, C.FP16_LOSS_SCALE_WINDOW,
                                       C.FP16_LOSS_SCALE_WINDOW_DEFAULT)),
            hysteresis=int(_get(d, C.FP16_HYSTERESIS, C.FP16_HYSTERESIS_DEFAULT)),
            min_loss_scale=float(_get(d, C.FP16_MIN_LOSS_SCALE,
                                      C.FP16_MIN_LOSS_SCALE_DEFAULT)),
        )

    @property
    def dynamic_loss_scale(self) -> bool:
        return self.loss_scale == 0.0


@dataclass
class ActivationCheckpointingConfig:
    partition_activations: bool = False
    contiguous_memory_optimization: bool = False
    number_checkpoints: Optional[int] = None
    synchronize_checkpoint_boundary: bool = False
    profile: bool = False
    cpu_checkpointing: bool = False

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> "ActivationCheckpointingConfig":
        d = d or {}
        return cls(
            partition_activations=bool(_get(d, C.ACT_CHKPT_PARTITION_ACTIVATIONS, False)),
            contiguous_memory_optimization=bool(
                _get(d, C.ACT_CHKPT_CONTIGUOUS_MEMORY_OPTIMIZATION, False)),
            number_checkpoints=d.get(C.ACT_CHKPT_NUMBER_CHECKPOINTS),
            synchronize_checkpoint_boundary=bool(
                _get(d, C.ACT_CHKPT_SYNCHRONIZE_CHECKPOINT_BOUNDARY, False)),
            profile=bool(_get(d, C.ACT_CHKPT_PROFILE, False)),
            cpu_checkpointing=bool(_get(d, C.ACT_CHKPT_CPU_CHECKPOINTING, False)),
        )


@dataclass
class FlopsProfilerConfig:
    enabled: bool = False
    profile_step: int = 1
    module_depth: int = -1
    top_modules: int = 1
    detailed: bool = True
    output_file: Optional[str] = None

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> "FlopsProfilerConfig":
        d = d or {}
        return cls(
            enabled=bool(_get(d, C.FLOPS_PROFILER_ENABLED, False)),
            profile_step=int(_get(d, C.FLOPS_PROFILER_PROFILE_STEP, 1)),
            module_depth=int(_get(d, C.FLOPS_PROFILER_MODULE_DEPTH, -1)),
            top_modules=int(_get(d, C.FLOPS_PROFILER_TOP_MODULES, 1)),
            detailed=bool(_get(d, C.FLOPS_PROFILER_DETAILED, True)),
            output_file=d.get(C.FLOPS_PROFILER_OUTPUT_FILE),
        )


@dataclass
class PLDConfig:
    enabled: bool = False
    theta: float = 1.0
    gamma: float = 0.001

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> "PLDConfig":
        d = d or {}
        return cls(enabled=bool(_get(d, C.PLD_ENABLED, False)),
                   theta=float(_get(d, C.PLD_THETA, 1.0)),
                   gamma=float(_get(d, C.PLD_GAMMA, 0.001)))


@dataclass
class ResilienceCheckpointConfig:
    """The async-checkpoint knobs (resilience/checkpoint.py)."""

    dir: str = ""
    interval: int = C.RESILIENCE_CKPT_INTERVAL_DEFAULT
    keep_last: int = C.RESILIENCE_CKPT_KEEP_LAST_DEFAULT
    max_retries: int = C.RESILIENCE_CKPT_MAX_RETRIES_DEFAULT
    backoff_seconds: float = C.RESILIENCE_CKPT_BACKOFF_DEFAULT
    async_write: bool = C.RESILIENCE_CKPT_ASYNC_DEFAULT

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> "ResilienceCheckpointConfig":
        d = d or {}
        cfg = cls(
            dir=str(_get(d, C.RESILIENCE_CKPT_DIR, "")),
            interval=int(_get(d, C.RESILIENCE_CKPT_INTERVAL,
                              C.RESILIENCE_CKPT_INTERVAL_DEFAULT)),
            keep_last=int(_get(d, C.RESILIENCE_CKPT_KEEP_LAST,
                               C.RESILIENCE_CKPT_KEEP_LAST_DEFAULT)),
            max_retries=int(_get(d, C.RESILIENCE_CKPT_MAX_RETRIES,
                                 C.RESILIENCE_CKPT_MAX_RETRIES_DEFAULT)),
            backoff_seconds=float(_get(d, C.RESILIENCE_CKPT_BACKOFF,
                                       C.RESILIENCE_CKPT_BACKOFF_DEFAULT)),
            async_write=bool(_get(d, C.RESILIENCE_CKPT_ASYNC,
                                  C.RESILIENCE_CKPT_ASYNC_DEFAULT)),
        )
        if cfg.interval < 1:
            raise ConfigError("resilience.checkpoint.interval must be >= 1")
        if cfg.keep_last < 1:
            raise ConfigError("resilience.checkpoint.keep_last must be >= 1")
        if cfg.max_retries < 0:
            raise ConfigError("resilience.checkpoint.max_retries must be >= 0")
        return cfg


@dataclass
class ResilienceConfig:
    """Preemption-aware training (resilience/): auto checkpointing every
    ``checkpoint.interval`` steps off the step path, auto-resume from the
    newest complete manifest, and a deterministic fault-injection plan
    (``fault_injection`` keys = FaultPlan fields; ``DSTPU_FAULT_PLAN`` env
    JSON overrides them)."""

    enabled: bool = False
    checkpoint: ResilienceCheckpointConfig = field(
        default_factory=ResilienceCheckpointConfig)
    auto_resume: bool = C.RESILIENCE_AUTO_RESUME_DEFAULT
    fault_injection: Dict[str, Any] = field(default_factory=dict)

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> "ResilienceConfig":
        d = d or {}
        cfg = cls(
            enabled=bool(_get(d, C.RESILIENCE_ENABLED, False)),
            checkpoint=ResilienceCheckpointConfig.from_dict(
                d.get(C.RESILIENCE_CHECKPOINT)),
            auto_resume=bool(_get(d, C.RESILIENCE_AUTO_RESUME,
                                  C.RESILIENCE_AUTO_RESUME_DEFAULT)),
            fault_injection=dict(d.get(C.RESILIENCE_FAULT_INJECTION) or {}),
        )
        if cfg.enabled and not cfg.checkpoint.dir:
            raise ConfigError(
                "resilience.enabled requires resilience.checkpoint.dir "
                "(where manifests/shards are committed)")
        return cfg


@dataclass
class LiveEvictionConfig:
    """Straggler-eviction knobs of the live-elasticity loop
    (resilience/elastic.py): evict a fleet-flagged persistent straggler
    only when the goodput cost model says the projected throughput gain
    over ``horizon_steps`` beats ``min_gain_factor`` x the measured
    in-process reshard cost."""

    enabled: bool = C.ELASTICITY_LIVE_EVICTION_ENABLED_DEFAULT
    horizon_steps: int = C.ELASTICITY_LIVE_EVICTION_HORIZON_DEFAULT
    min_gain_factor: float = C.ELASTICITY_LIVE_EVICTION_MIN_GAIN_DEFAULT
    assumed_reshard_sec: float = \
        C.ELASTICITY_LIVE_EVICTION_ASSUMED_RESHARD_DEFAULT

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> "LiveEvictionConfig":
        d = d or {}
        cfg = cls(
            enabled=bool(_get(d, C.ELASTICITY_LIVE_EVICTION_ENABLED,
                              C.ELASTICITY_LIVE_EVICTION_ENABLED_DEFAULT)),
            horizon_steps=int(_get(d, C.ELASTICITY_LIVE_EVICTION_HORIZON,
                                   C.ELASTICITY_LIVE_EVICTION_HORIZON_DEFAULT)),
            min_gain_factor=float(_get(
                d, C.ELASTICITY_LIVE_EVICTION_MIN_GAIN,
                C.ELASTICITY_LIVE_EVICTION_MIN_GAIN_DEFAULT)),
            assumed_reshard_sec=float(_get(
                d, C.ELASTICITY_LIVE_EVICTION_ASSUMED_RESHARD,
                C.ELASTICITY_LIVE_EVICTION_ASSUMED_RESHARD_DEFAULT)),
        )
        if cfg.horizon_steps < 1:
            raise ConfigError(
                "elasticity.live.eviction.horizon_steps must be >= 1")
        if cfg.min_gain_factor <= 0:
            raise ConfigError(
                "elasticity.live.eviction.min_gain_factor must be > 0")
        if cfg.assumed_reshard_sec <= 0:
            raise ConfigError(
                "elasticity.live.eviction.assumed_reshard_sec must be > 0")
        return cfg


@dataclass
class LiveElasticityConfig:
    """``elasticity.live`` — in-process live elasticity
    (resilience/elastic.py; docs/RESILIENCE.md "Live elasticity"): catch
    the preemption advance warning (SIGTERM inside ``grace_seconds``),
    drain, reshard onto the surviving chips in the SAME process, re-admit
    a returning slice at the next snapshot boundary, and close the
    straggler-eviction loop. Disabled (the default) is provably free: no
    signal handler installed, zero extra syncs, lowered step unchanged."""

    enabled: bool = C.ELASTICITY_LIVE_ENABLED_DEFAULT
    grace_seconds: float = C.ELASTICITY_LIVE_GRACE_DEFAULT
    check_interval_steps: int = C.ELASTICITY_LIVE_CHECK_INTERVAL_DEFAULT
    exit_code: int = C.ELASTIC_PREEMPT_EXIT_CODE_DEFAULT
    eviction: LiveEvictionConfig = field(default_factory=LiveEvictionConfig)

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> "LiveElasticityConfig":
        d = d or {}
        cfg = cls(
            enabled=bool(_get(d, C.ELASTICITY_LIVE_ENABLED,
                              C.ELASTICITY_LIVE_ENABLED_DEFAULT)),
            grace_seconds=float(_get(d, C.ELASTICITY_LIVE_GRACE,
                                     C.ELASTICITY_LIVE_GRACE_DEFAULT)),
            check_interval_steps=int(_get(
                d, C.ELASTICITY_LIVE_CHECK_INTERVAL,
                C.ELASTICITY_LIVE_CHECK_INTERVAL_DEFAULT)),
            exit_code=int(_get(d, C.ELASTICITY_LIVE_EXIT_CODE,
                               C.ELASTIC_PREEMPT_EXIT_CODE_DEFAULT)),
            eviction=LiveEvictionConfig.from_dict(
                d.get(C.ELASTICITY_LIVE_EVICTION)),
        )
        if cfg.enabled and cfg.grace_seconds <= 0:
            raise ConfigError("elasticity.live.grace_seconds must be > 0")
        if cfg.check_interval_steps < 1:
            raise ConfigError(
                "elasticity.live.check_interval_steps must be >= 1")
        if not 0 < cfg.exit_code < 256:
            raise ConfigError(
                "elasticity.live.exit_code must be in 1..255")
        return cfg


@dataclass
class GuardrailsDetectorConfig:
    """Anomaly-detector knobs (guardrails/detector.py)."""

    zscore_threshold: float = C.GUARDRAILS_DET_ZSCORE_DEFAULT
    warmup_steps: int = C.GUARDRAILS_DET_WARMUP_DEFAULT
    ewma_alpha: float = C.GUARDRAILS_DET_EWMA_ALPHA_DEFAULT
    track_grad_norm: bool = C.GUARDRAILS_DET_TRACK_GRAD_NORM_DEFAULT
    # In-device skip-on-nonfinite-grads for bf16/fp32 runs (the fp16 path
    # already has the loss-scaler skip). Default OFF: the predicate rides
    # inside the jitted step, so the gate must be an explicit opt-in.
    check_nonfinite_grads: bool = C.GUARDRAILS_DET_NONFINITE_GRADS_DEFAULT

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> "GuardrailsDetectorConfig":
        d = d or {}
        cfg = cls(
            zscore_threshold=float(_get(d, C.GUARDRAILS_DET_ZSCORE,
                                        C.GUARDRAILS_DET_ZSCORE_DEFAULT)),
            warmup_steps=int(_get(d, C.GUARDRAILS_DET_WARMUP,
                                  C.GUARDRAILS_DET_WARMUP_DEFAULT)),
            ewma_alpha=float(_get(d, C.GUARDRAILS_DET_EWMA_ALPHA,
                                  C.GUARDRAILS_DET_EWMA_ALPHA_DEFAULT)),
            track_grad_norm=bool(_get(d, C.GUARDRAILS_DET_TRACK_GRAD_NORM,
                                      C.GUARDRAILS_DET_TRACK_GRAD_NORM_DEFAULT)),
            check_nonfinite_grads=bool(
                _get(d, C.GUARDRAILS_DET_NONFINITE_GRADS,
                     C.GUARDRAILS_DET_NONFINITE_GRADS_DEFAULT)),
        )
        if cfg.zscore_threshold <= 0:
            raise ConfigError("guardrails.detector.zscore_threshold must be > 0")
        if cfg.warmup_steps < 1:
            raise ConfigError("guardrails.detector.warmup_steps must be >= 1")
        if not 0.0 < cfg.ewma_alpha <= 1.0:
            raise ConfigError("guardrails.detector.ewma_alpha must be in (0, 1]")
        return cfg


@dataclass
class GuardrailsRollbackConfig:
    """In-memory rollback knobs (guardrails/rollback.py)."""

    enabled: bool = C.GUARDRAILS_RB_ENABLED_DEFAULT
    snapshot_interval: int = C.GUARDRAILS_RB_SNAPSHOT_INTERVAL_DEFAULT
    ring_size: int = C.GUARDRAILS_RB_RING_SIZE_DEFAULT
    consecutive_spikes: int = C.GUARDRAILS_RB_CONSECUTIVE_SPIKES_DEFAULT
    skip_batches: int = C.GUARDRAILS_RB_SKIP_BATCHES_DEFAULT
    lr_decay: float = C.GUARDRAILS_RB_LR_DECAY_DEFAULT
    max_rollbacks: int = C.GUARDRAILS_RB_MAX_ROLLBACKS_DEFAULT
    escalate_to_disk: bool = C.GUARDRAILS_RB_ESCALATE_DEFAULT

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> "GuardrailsRollbackConfig":
        d = d or {}
        cfg = cls(
            enabled=bool(_get(d, C.GUARDRAILS_RB_ENABLED,
                              C.GUARDRAILS_RB_ENABLED_DEFAULT)),
            snapshot_interval=int(_get(d, C.GUARDRAILS_RB_SNAPSHOT_INTERVAL,
                                       C.GUARDRAILS_RB_SNAPSHOT_INTERVAL_DEFAULT)),
            ring_size=int(_get(d, C.GUARDRAILS_RB_RING_SIZE,
                               C.GUARDRAILS_RB_RING_SIZE_DEFAULT)),
            consecutive_spikes=int(_get(d, C.GUARDRAILS_RB_CONSECUTIVE_SPIKES,
                                        C.GUARDRAILS_RB_CONSECUTIVE_SPIKES_DEFAULT)),
            skip_batches=int(_get(d, C.GUARDRAILS_RB_SKIP_BATCHES,
                                  C.GUARDRAILS_RB_SKIP_BATCHES_DEFAULT)),
            lr_decay=float(_get(d, C.GUARDRAILS_RB_LR_DECAY,
                                C.GUARDRAILS_RB_LR_DECAY_DEFAULT)),
            max_rollbacks=int(_get(d, C.GUARDRAILS_RB_MAX_ROLLBACKS,
                                   C.GUARDRAILS_RB_MAX_ROLLBACKS_DEFAULT)),
            escalate_to_disk=bool(_get(d, C.GUARDRAILS_RB_ESCALATE,
                                       C.GUARDRAILS_RB_ESCALATE_DEFAULT)),
        )
        if cfg.snapshot_interval < 1:
            raise ConfigError("guardrails.rollback.snapshot_interval must be >= 1")
        if cfg.ring_size < 1:
            raise ConfigError("guardrails.rollback.ring_size must be >= 1")
        if cfg.consecutive_spikes < 1:
            raise ConfigError("guardrails.rollback.consecutive_spikes must be >= 1")
        if cfg.skip_batches < 0:
            raise ConfigError("guardrails.rollback.skip_batches must be >= 0")
        if not 0.0 < cfg.lr_decay <= 1.0:
            raise ConfigError("guardrails.rollback.lr_decay must be in (0, 1]")
        if cfg.max_rollbacks < 1:
            raise ConfigError("guardrails.rollback.max_rollbacks must be >= 1")
        return cfg


@dataclass
class GuardrailsWatchdogConfig:
    """Step-deadline watchdog knobs (guardrails/watchdog.py)."""

    enabled: bool = C.GUARDRAILS_WD_ENABLED_DEFAULT
    step_timeout_seconds: float = C.GUARDRAILS_WD_TIMEOUT_DEFAULT
    poll_interval_seconds: Optional[float] = None
    crashdump_dir: str = C.GUARDRAILS_WD_CRASHDUMP_DIR_DEFAULT
    exit_code: int = C.GUARDRAILS_WATCHDOG_EXIT_CODE_DEFAULT

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> "GuardrailsWatchdogConfig":
        d = d or {}
        cfg = cls(
            enabled=bool(_get(d, C.GUARDRAILS_WD_ENABLED,
                              C.GUARDRAILS_WD_ENABLED_DEFAULT)),
            step_timeout_seconds=float(_get(d, C.GUARDRAILS_WD_TIMEOUT,
                                            C.GUARDRAILS_WD_TIMEOUT_DEFAULT)),
            poll_interval_seconds=(
                float(d[C.GUARDRAILS_WD_POLL])
                if d.get(C.GUARDRAILS_WD_POLL) is not None else None),
            crashdump_dir=str(_get(d, C.GUARDRAILS_WD_CRASHDUMP_DIR,
                                   C.GUARDRAILS_WD_CRASHDUMP_DIR_DEFAULT)),
            exit_code=int(_get(d, C.GUARDRAILS_WD_EXIT_CODE,
                               C.GUARDRAILS_WATCHDOG_EXIT_CODE_DEFAULT)),
        )
        if cfg.enabled and cfg.step_timeout_seconds <= 0:
            raise ConfigError(
                "guardrails.watchdog.step_timeout_seconds must be > 0")
        if (cfg.poll_interval_seconds is not None
                and float(cfg.poll_interval_seconds) <= 0):
            raise ConfigError(
                "guardrails.watchdog.poll_interval_seconds must be > 0 "
                "(a non-positive poll busy-spins the watchdog thread)")
        if not 0 < cfg.exit_code < 256:
            raise ConfigError("guardrails.watchdog.exit_code must be in 1..255")
        return cfg


@dataclass
class GuardrailsConfig:
    """Unattended-training guardrails (guardrails/; docs/RESILIENCE.md
    "Guardrails"): EWMA/z-score anomaly detection over loss + grad norm,
    in-memory rollback from a bounded snapshot ring, and a step-deadline
    watchdog. Disabled (the default) the engine takes the exact pre-
    guardrails step path: no host fetches, no device syncs, no snapshots."""

    enabled: bool = False
    detector: GuardrailsDetectorConfig = field(
        default_factory=GuardrailsDetectorConfig)
    rollback: GuardrailsRollbackConfig = field(
        default_factory=GuardrailsRollbackConfig)
    watchdog: GuardrailsWatchdogConfig = field(
        default_factory=GuardrailsWatchdogConfig)

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> "GuardrailsConfig":
        d = d or {}
        return cls(
            enabled=bool(_get(d, C.GUARDRAILS_ENABLED, False)),
            detector=GuardrailsDetectorConfig.from_dict(
                d.get(C.GUARDRAILS_DETECTOR)),
            rollback=GuardrailsRollbackConfig.from_dict(
                d.get(C.GUARDRAILS_ROLLBACK)),
            watchdog=GuardrailsWatchdogConfig.from_dict(
                d.get(C.GUARDRAILS_WATCHDOG)),
        )

    @property
    def nonfinite_grad_check(self) -> bool:
        """The jitted-step gate: bf16/fp32 skip-on-nonfinite is active only
        when guardrails are on AND the detector opted in."""
        return self.enabled and self.detector.check_nonfinite_grads


@dataclass
class MeshConfig:
    """Named parallel axes. Sizes of 1 mean the axis is unused.

    ``data`` may be -1 (infer: world_size // product(other axes)).
    """

    data: int = -1
    model: int = 1
    pipe: int = 1
    sequence: int = 1
    expert: int = 1
    slices: int = 1     # DCN-outer slice count (multi-slice/multi-pod)

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> "MeshConfig":
        d = d or {}
        cfg = cls(
            data=int(_get(d, C.MESH_DATA, -1)),
            model=int(_get(d, C.MESH_MODEL, 1)),
            pipe=int(_get(d, C.MESH_PIPE, 1)),
            sequence=int(_get(d, C.MESH_SEQUENCE, 1)),
            expert=int(_get(d, C.MESH_EXPERT, 1)),
            slices=int(_get(d, C.MESH_SLICES, 1)),
        )
        for name in ("model", "pipe", "sequence", "expert", "slices"):
            if getattr(cfg, name) < 1:
                raise ConfigError(f"mesh.{name} must be >= 1")
        return cfg

    def resolve_data(self, world_size: int) -> int:
        fixed = (self.model * self.pipe * self.sequence * self.expert *
                 self.slices)
        if world_size % fixed != 0:
            raise ConfigError(
                f"world size {world_size} not divisible by mesh axes product {fixed}")
        data = world_size // fixed
        if self.data not in (-1, data):
            raise ConfigError(
                f"mesh.data={self.data} inconsistent with world={world_size}, "
                f"slices×model×pipe×sequence×expert={fixed}")
        # The GLOBAL data-parallel degree spans both the ICI-inner `data`
        # axis and the DCN-outer `dcn` axis (batches shard over both).
        return data * self.slices


@dataclass
class CommConfig:
    """``comm`` block — the hierarchical quantized gradient-sync strategy
    (comm/grad_sync.py, docs/PERFORMANCE.md).

    ``hierarchical``: ``auto`` engages the explicit bucketed sync on
    multi-slice (dcn > 1) meshes when the step path supports it; ``on``
    forces it (raising on incompatible configurations); ``off`` keeps
    today's implicit pjit resharding, bit-identical.
    ``dcn_quant_bits``: the DCN wire dtype — 8 (blockwise int8 + per-block
    fp32 scales), 16 (bf16 passthrough) or 32 (fp32 passthrough).
    ``quant_block_size``: elements per quantization block (per-block
    absmax scale granularity).
    ``bucket_mb``: flat gradient bucket size in MiB (the unit of the ICI
    reduce-scatter and DCN all-reduce).
    ``overlap_grad_sync``: the overlapped schedule (docs/PERFORMANCE.md
    "Overlapped gradient sync") — readiness-ordered per-bucket ICI
    reduce-scatter during backward plus a double-buffered per-microstep
    DCN all-reduce. ``auto`` (default) engages whenever the hierarchical
    sync does; ``off`` keeps the GAS-boundary schedule; ``on`` is
    explicit opt-in (same effect as auto — the incompatible paths are
    already excluded at ``hierarchical`` resolution).
    ``ici_gbps`` / ``dcn_gbps``: nominal per-device link bandwidths behind
    the modeled ``comm/exposed_frac`` device-time attribution
    (docs/OBSERVABILITY.md "Fleet observability").
    """

    hierarchical: str = C.COMM_HIERARCHICAL_DEFAULT
    dcn_quant_bits: int = C.COMM_DCN_QUANT_BITS_DEFAULT
    quant_block_size: int = C.COMM_QUANT_BLOCK_SIZE_DEFAULT
    bucket_mb: float = C.COMM_BUCKET_MB_DEFAULT
    overlap_grad_sync: str = C.COMM_OVERLAP_GRAD_SYNC_DEFAULT
    ici_gbps: float = C.COMM_ICI_GBPS_DEFAULT
    dcn_gbps: float = C.COMM_DCN_GBPS_DEFAULT

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> "CommConfig":
        d = d or {}
        cfg = cls(
            hierarchical=str(_get(d, C.COMM_HIERARCHICAL,
                                  C.COMM_HIERARCHICAL_DEFAULT)).lower(),
            dcn_quant_bits=int(_get(d, C.COMM_DCN_QUANT_BITS,
                                    C.COMM_DCN_QUANT_BITS_DEFAULT)),
            quant_block_size=int(_get(d, C.COMM_QUANT_BLOCK_SIZE,
                                      C.COMM_QUANT_BLOCK_SIZE_DEFAULT)),
            bucket_mb=float(_get(d, C.COMM_BUCKET_MB,
                                 C.COMM_BUCKET_MB_DEFAULT)),
            overlap_grad_sync=str(_get(
                d, C.COMM_OVERLAP_GRAD_SYNC,
                C.COMM_OVERLAP_GRAD_SYNC_DEFAULT)).lower(),
            ici_gbps=float(_get(d, C.COMM_ICI_GBPS,
                                C.COMM_ICI_GBPS_DEFAULT)),
            dcn_gbps=float(_get(d, C.COMM_DCN_GBPS,
                                C.COMM_DCN_GBPS_DEFAULT)),
        )
        if cfg.hierarchical not in ("auto", "on", "off"):
            raise ConfigError(
                f"comm.hierarchical must be auto|on|off, got "
                f"'{cfg.hierarchical}'")
        if cfg.dcn_quant_bits not in (8, 16, 32):
            raise ConfigError(
                f"comm.dcn_quant_bits must be 8 (int8), 16 (bf16) or 32 "
                f"(fp32), got {cfg.dcn_quant_bits}")
        if cfg.quant_block_size <= 0:
            raise ConfigError(
                f"comm.quant_block_size must be positive, got "
                f"{cfg.quant_block_size}")
        if cfg.bucket_mb <= 0:
            raise ConfigError(
                f"comm.bucket_mb must be positive, got {cfg.bucket_mb}")
        if cfg.overlap_grad_sync not in ("auto", "on", "off"):
            raise ConfigError(
                f"comm.overlap_grad_sync must be auto|on|off, got "
                f"'{cfg.overlap_grad_sync}'")
        if cfg.ici_gbps <= 0 or cfg.dcn_gbps <= 0:
            raise ConfigError(
                f"comm.ici_gbps/dcn_gbps must be positive, got "
                f"{cfg.ici_gbps}/{cfg.dcn_gbps}")
        return cfg


@dataclass
class AIOConfig:
    block_size: int = C.AIO_BLOCK_SIZE_DEFAULT
    queue_depth: int = C.AIO_QUEUE_DEPTH_DEFAULT
    thread_count: int = C.AIO_THREAD_COUNT_DEFAULT
    single_submit: bool = C.AIO_SINGLE_SUBMIT_DEFAULT
    overlap_events: bool = C.AIO_OVERLAP_EVENTS_DEFAULT

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> "AIOConfig":
        d = d or {}
        return cls(
            block_size=int(_get(d, C.AIO_BLOCK_SIZE, C.AIO_BLOCK_SIZE_DEFAULT)),
            queue_depth=int(_get(d, C.AIO_QUEUE_DEPTH, C.AIO_QUEUE_DEPTH_DEFAULT)),
            thread_count=int(_get(d, C.AIO_THREAD_COUNT, C.AIO_THREAD_COUNT_DEFAULT)),
            single_submit=bool(_get(d, C.AIO_SINGLE_SUBMIT, C.AIO_SINGLE_SUBMIT_DEFAULT)),
            overlap_events=bool(_get(d, C.AIO_OVERLAP_EVENTS, C.AIO_OVERLAP_EVENTS_DEFAULT)),
        )


@dataclass
class TelemetryTraceConfig:
    """Step tracer knobs (telemetry/tracer.py)."""

    enabled: bool = C.TELEMETRY_TRACE_ENABLED_DEFAULT
    file: str = C.TELEMETRY_TRACE_FILE_DEFAULT
    sync_spans: bool = C.TELEMETRY_TRACE_SYNC_SPANS_DEFAULT
    jax_profiler_dir: Optional[str] = None

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> "TelemetryTraceConfig":
        d = d or {}
        return cls(
            enabled=bool(_get(d, C.TELEMETRY_TRACE_ENABLED,
                              C.TELEMETRY_TRACE_ENABLED_DEFAULT)),
            file=str(_get(d, C.TELEMETRY_TRACE_FILE,
                          C.TELEMETRY_TRACE_FILE_DEFAULT)),
            sync_spans=bool(_get(d, C.TELEMETRY_TRACE_SYNC_SPANS,
                                 C.TELEMETRY_TRACE_SYNC_SPANS_DEFAULT)),
            jax_profiler_dir=d.get(C.TELEMETRY_TRACE_JAX_PROFILER_DIR),
        )


@dataclass
class TelemetryMetricsConfig:
    """Metrics registry sinks (telemetry/registry.py)."""

    sinks: tuple = C.TELEMETRY_METRICS_SINKS_DEFAULT
    file: str = C.TELEMETRY_METRICS_FILE_DEFAULT

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> "TelemetryMetricsConfig":
        d = d or {}
        sinks = tuple(_get(d, C.TELEMETRY_METRICS_SINKS,
                           C.TELEMETRY_METRICS_SINKS_DEFAULT))
        for s in sinks:
            if s not in C.TELEMETRY_METRICS_VALID_SINKS:
                raise ConfigError(
                    f"telemetry.metrics.sinks: unknown sink {s!r} (valid: "
                    f"{list(C.TELEMETRY_METRICS_VALID_SINKS)})")
        return cls(sinks=sinks,
                   file=str(_get(d, C.TELEMETRY_METRICS_FILE,
                                 C.TELEMETRY_METRICS_FILE_DEFAULT)))


@dataclass
class TelemetryFleetConfig:
    """Fleet observability knobs (telemetry/fleet.py): cross-host metric
    aggregation at flush boundaries + rolling-window straggler detection.
    Default off — enabled it adds one tiny jitted all-gather and one host
    fetch per flush (never on the step path)."""

    enabled: bool = C.TELEMETRY_FLEET_ENABLED_DEFAULT
    window: int = C.TELEMETRY_FLEET_WINDOW_DEFAULT
    min_window: int = C.TELEMETRY_FLEET_MIN_WINDOW_DEFAULT
    zscore: float = C.TELEMETRY_FLEET_ZSCORE_DEFAULT
    persist: int = C.TELEMETRY_FLEET_PERSIST_DEFAULT
    breakdown_file: str = C.TELEMETRY_FLEET_BREAKDOWN_FILE_DEFAULT

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> "TelemetryFleetConfig":
        d = d or {}
        cfg = cls(
            enabled=bool(_get(d, C.TELEMETRY_FLEET_ENABLED,
                              C.TELEMETRY_FLEET_ENABLED_DEFAULT)),
            window=int(_get(d, C.TELEMETRY_FLEET_WINDOW,
                            C.TELEMETRY_FLEET_WINDOW_DEFAULT)),
            min_window=int(_get(d, C.TELEMETRY_FLEET_MIN_WINDOW,
                                C.TELEMETRY_FLEET_MIN_WINDOW_DEFAULT)),
            zscore=float(_get(d, C.TELEMETRY_FLEET_ZSCORE,
                              C.TELEMETRY_FLEET_ZSCORE_DEFAULT)),
            persist=int(_get(d, C.TELEMETRY_FLEET_PERSIST,
                             C.TELEMETRY_FLEET_PERSIST_DEFAULT)),
            breakdown_file=str(_get(d, C.TELEMETRY_FLEET_BREAKDOWN_FILE,
                                    C.TELEMETRY_FLEET_BREAKDOWN_FILE_DEFAULT)),
        )
        if cfg.min_window < 1 or cfg.window < cfg.min_window:
            raise ConfigError(
                f"telemetry.fleet: need window >= min_window >= 1, got "
                f"window={cfg.window} min_window={cfg.min_window}")
        if cfg.zscore <= 0:
            raise ConfigError(
                f"telemetry.fleet.zscore must be positive, got {cfg.zscore}")
        if cfg.persist < 1:
            raise ConfigError(
                f"telemetry.fleet.persist must be >= 1, got {cfg.persist}")
        # The supervisor and the stdlib-only fleet_report discover the
        # breakdown by the fleet_breakdown*.json pattern (they cannot see
        # this config) — a name outside it would be written and then
        # silently never read.
        if not (cfg.breakdown_file.startswith("fleet_breakdown")
                and cfg.breakdown_file.endswith(".json")):
            raise ConfigError(
                "telemetry.fleet.breakdown_file must match "
                f"'fleet_breakdown*.json' (readers discover it by that "
                f"pattern), got '{cfg.breakdown_file}'")
        return cfg


@dataclass
class TelemetryMemoryConfig:
    """Memory observatory knobs (telemetry/memory.py): XLA memory
    attribution + model-state ledger + capacity planner + OOM forensics.
    Default off — enabled it adds one AOT lower+compile per step
    function and per-step headroom gauges (riding the HBM stats fetch
    the engine gauges already pay for); never any change to the step
    jaxpr."""

    enabled: bool = C.TELEMETRY_MEMORY_ENABLED_DEFAULT
    headroom_warn_frac: float = C.TELEMETRY_MEMORY_HEADROOM_WARN_FRAC_DEFAULT
    crashdump_dir: str = C.TELEMETRY_MEMORY_CRASHDUMP_DIR_DEFAULT
    oom_exit_code: int = C.MEMORY_OOM_EXIT_CODE_DEFAULT
    plan_at_init: bool = C.TELEMETRY_MEMORY_PLAN_AT_INIT_DEFAULT
    plan_file: str = C.TELEMETRY_MEMORY_PLAN_FILE_DEFAULT
    activation_bytes_per_sample: float = C.TELEMETRY_MEMORY_ACT_BYTES_DEFAULT
    hbm_limit_gb: Optional[float] = None

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> \
            "TelemetryMemoryConfig":
        d = d or {}
        cfg = cls(
            enabled=bool(_get(d, C.TELEMETRY_MEMORY_ENABLED,
                              C.TELEMETRY_MEMORY_ENABLED_DEFAULT)),
            headroom_warn_frac=float(_get(
                d, C.TELEMETRY_MEMORY_HEADROOM_WARN_FRAC,
                C.TELEMETRY_MEMORY_HEADROOM_WARN_FRAC_DEFAULT)),
            crashdump_dir=str(_get(d, C.TELEMETRY_MEMORY_CRASHDUMP_DIR,
                                   C.TELEMETRY_MEMORY_CRASHDUMP_DIR_DEFAULT)),
            oom_exit_code=int(_get(d, C.TELEMETRY_MEMORY_OOM_EXIT_CODE,
                                   C.MEMORY_OOM_EXIT_CODE_DEFAULT)),
            plan_at_init=bool(_get(d, C.TELEMETRY_MEMORY_PLAN_AT_INIT,
                                   C.TELEMETRY_MEMORY_PLAN_AT_INIT_DEFAULT)),
            plan_file=str(_get(d, C.TELEMETRY_MEMORY_PLAN_FILE,
                               C.TELEMETRY_MEMORY_PLAN_FILE_DEFAULT)),
            activation_bytes_per_sample=float(_get(
                d, C.TELEMETRY_MEMORY_ACT_BYTES,
                C.TELEMETRY_MEMORY_ACT_BYTES_DEFAULT)),
            hbm_limit_gb=(float(d[C.TELEMETRY_MEMORY_HBM_LIMIT_GB])
                          if d.get(C.TELEMETRY_MEMORY_HBM_LIMIT_GB)
                          is not None else None),
        )
        if not (0.0 <= cfg.headroom_warn_frac <= 1.0):
            raise ConfigError(
                f"telemetry.memory.headroom_warn_frac must be in [0, 1], "
                f"got {cfg.headroom_warn_frac}")
        if not (1 <= cfg.oom_exit_code <= 255):
            raise ConfigError(
                f"telemetry.memory.oom_exit_code must be in [1, 255], got "
                f"{cfg.oom_exit_code}")
        if cfg.hbm_limit_gb is not None and cfg.hbm_limit_gb <= 0:
            raise ConfigError(
                f"telemetry.memory.hbm_limit_gb must be positive, got "
                f"{cfg.hbm_limit_gb}")
        # The planner file is discovered by pattern by the stdlib-only
        # memory_report (same argument as fleet.breakdown_file).
        if not (cfg.plan_file.startswith("memory_plan")
                and cfg.plan_file.endswith(".json")):
            raise ConfigError(
                "telemetry.memory.plan_file must match 'memory_plan*.json' "
                f"(tools/memory_report.py discovers it by that pattern), "
                f"got '{cfg.plan_file}'")
        return cfg


@dataclass
class TelemetryDevicetimeConfig:
    """Device-time observatory knobs (telemetry/devicetime.py): scheduled
    ``jax.profiler`` captures (``capture_steps`` steps every
    ``every_steps``, host-scoped dirs, keep-last-``keep_last`` GC) parsed
    into measured ``devicetime/*`` attribution, roofline classification
    and ``comm/measured_exposed_frac``. Default off — enabled, all work
    happens at capture boundaries; the in-between step path pays two
    integer comparisons and the step jaxpr never changes."""

    enabled: bool = C.TELEMETRY_DEVICETIME_ENABLED_DEFAULT
    capture_steps: int = C.TELEMETRY_DEVICETIME_CAPTURE_STEPS_DEFAULT
    every_steps: int = C.TELEMETRY_DEVICETIME_EVERY_STEPS_DEFAULT
    keep_last: int = C.TELEMETRY_DEVICETIME_KEEP_LAST_DEFAULT
    dir: str = C.TELEMETRY_DEVICETIME_DIR_DEFAULT
    top_k: int = C.TELEMETRY_DEVICETIME_TOP_K_DEFAULT
    divergence_warn: float = C.TELEMETRY_DEVICETIME_DIVERGENCE_WARN_DEFAULT
    hbm_gbps: Optional[float] = None

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> \
            "TelemetryDevicetimeConfig":
        d = d or {}
        cfg = cls(
            enabled=bool(_get(d, C.TELEMETRY_DEVICETIME_ENABLED,
                              C.TELEMETRY_DEVICETIME_ENABLED_DEFAULT)),
            capture_steps=int(_get(
                d, C.TELEMETRY_DEVICETIME_CAPTURE_STEPS,
                C.TELEMETRY_DEVICETIME_CAPTURE_STEPS_DEFAULT)),
            every_steps=int(_get(
                d, C.TELEMETRY_DEVICETIME_EVERY_STEPS,
                C.TELEMETRY_DEVICETIME_EVERY_STEPS_DEFAULT)),
            keep_last=int(_get(d, C.TELEMETRY_DEVICETIME_KEEP_LAST,
                               C.TELEMETRY_DEVICETIME_KEEP_LAST_DEFAULT)),
            dir=str(_get(d, C.TELEMETRY_DEVICETIME_DIR,
                         C.TELEMETRY_DEVICETIME_DIR_DEFAULT)),
            top_k=int(_get(d, C.TELEMETRY_DEVICETIME_TOP_K,
                           C.TELEMETRY_DEVICETIME_TOP_K_DEFAULT)),
            divergence_warn=float(_get(
                d, C.TELEMETRY_DEVICETIME_DIVERGENCE_WARN,
                C.TELEMETRY_DEVICETIME_DIVERGENCE_WARN_DEFAULT)),
            hbm_gbps=(float(d[C.TELEMETRY_DEVICETIME_HBM_GBPS])
                      if d.get(C.TELEMETRY_DEVICETIME_HBM_GBPS) is not None
                      else None),
        )
        if cfg.capture_steps < 1:
            raise ConfigError(
                f"telemetry.devicetime.capture_steps must be >= 1, got "
                f"{cfg.capture_steps}")
        if cfg.every_steps <= cfg.capture_steps:
            raise ConfigError(
                f"telemetry.devicetime needs every_steps > capture_steps "
                f"(a capture must close before the next can open), got "
                f"every_steps={cfg.every_steps} "
                f"capture_steps={cfg.capture_steps}")
        if cfg.keep_last < 1:
            raise ConfigError(
                f"telemetry.devicetime.keep_last must be >= 1, got "
                f"{cfg.keep_last}")
        if cfg.top_k < 1:
            raise ConfigError(
                f"telemetry.devicetime.top_k must be >= 1, got {cfg.top_k}")
        if not (0.0 < cfg.divergence_warn <= 1.0):
            raise ConfigError(
                f"telemetry.devicetime.divergence_warn must be in (0, 1], "
                f"got {cfg.divergence_warn}")
        if cfg.hbm_gbps is not None and cfg.hbm_gbps <= 0:
            raise ConfigError(
                f"telemetry.devicetime.hbm_gbps must be positive, got "
                f"{cfg.hbm_gbps}")
        return cfg


@dataclass
class TelemetryNumericsConfig:
    """Numerics observatory knobs (telemetry/numerics.py): per-layer-group
    gradient/weight/update statistics + bf16/fp16 saturation and
    underflow-to-zero counters computed inside the jitted step as one
    small stacked aux array (fetched in a single transfer at flush
    boundaries), plus per-bucket DCN / KV-cache quantization-error
    gauges. Default off — the lowered step is then bit-identical to a
    numerics-less config; enabled, the stats ride the existing step
    program and the step path performs zero extra host fetches."""

    enabled: bool = C.TELEMETRY_NUMERICS_ENABLED_DEFAULT
    max_groups: int = C.TELEMETRY_NUMERICS_MAX_GROUPS_DEFAULT
    max_spike_dumps: int = C.TELEMETRY_NUMERICS_MAX_SPIKE_DUMPS_DEFAULT

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> \
            "TelemetryNumericsConfig":
        d = d or {}
        cfg = cls(
            enabled=bool(_get(d, C.TELEMETRY_NUMERICS_ENABLED,
                              C.TELEMETRY_NUMERICS_ENABLED_DEFAULT)),
            max_groups=int(_get(d, C.TELEMETRY_NUMERICS_MAX_GROUPS,
                                C.TELEMETRY_NUMERICS_MAX_GROUPS_DEFAULT)),
            max_spike_dumps=int(_get(
                d, C.TELEMETRY_NUMERICS_MAX_SPIKE_DUMPS,
                C.TELEMETRY_NUMERICS_MAX_SPIKE_DUMPS_DEFAULT)),
        )
        if cfg.max_groups < 1:
            raise ConfigError(
                f"telemetry.numerics.max_groups must be >= 1, got "
                f"{cfg.max_groups}")
        if cfg.max_spike_dumps < 0:
            raise ConfigError(
                f"telemetry.numerics.max_spike_dumps must be >= 0, got "
                f"{cfg.max_spike_dumps}")
        return cfg


@dataclass
class TelemetryRequestsConfig:
    """Request observatory knobs (telemetry/requests.py): per-request SLO
    accounting for the serve engine — exact lifetime partition, TPOT/e2e
    histograms, host-scoped ``requests.<host>.jsonl`` records, the
    engine-side serving-time partition, and the rolling decode-throughput
    window behind ``serving/tokens_per_sec_window``. Default off — the
    engine then holds no accountant (``None``) and its emitted tag set is
    byte-identical; enabled, every hook is host ``time.monotonic``
    arithmetic (zero device syncs)."""

    enabled: bool = C.TELEMETRY_REQUESTS_ENABLED_DEFAULT
    file: str = C.TELEMETRY_REQUESTS_FILE_DEFAULT
    window_sec: float = C.TELEMETRY_REQUESTS_WINDOW_SEC_DEFAULT

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> \
            "TelemetryRequestsConfig":
        d = d or {}
        cfg = cls(
            enabled=bool(_get(d, C.TELEMETRY_REQUESTS_ENABLED,
                              C.TELEMETRY_REQUESTS_ENABLED_DEFAULT)),
            file=str(_get(d, C.TELEMETRY_REQUESTS_FILE,
                          C.TELEMETRY_REQUESTS_FILE_DEFAULT)),
            window_sec=float(_get(
                d, C.TELEMETRY_REQUESTS_WINDOW_SEC,
                C.TELEMETRY_REQUESTS_WINDOW_SEC_DEFAULT)),
        )
        # Records are discovered by pattern by the stdlib-only slo_report
        # (same argument as memory.plan_file / fleet.breakdown_file).
        if not (cfg.file.startswith("requests")
                and cfg.file.endswith(".jsonl")):
            raise ConfigError(
                "telemetry.requests.file must match 'requests*.jsonl' "
                f"(tools/slo_report.py discovers records by that pattern), "
                f"got '{cfg.file}'")
        if cfg.window_sec <= 0:
            raise ConfigError(
                f"telemetry.requests.window_sec must be positive, got "
                f"{cfg.window_sec}")
        return cfg


@dataclass
class TelemetryConfig:
    """Unified observability (telemetry/; docs/OBSERVABILITY.md): metrics
    registry + Chrome-trace step tracer + recompilation detector. Disabled
    (the default) every hook is a no-op and the step path performs zero
    telemetry-originated device syncs."""

    enabled: bool = False
    dir: str = C.TELEMETRY_DIR_DEFAULT
    trace: TelemetryTraceConfig = field(default_factory=TelemetryTraceConfig)
    metrics: TelemetryMetricsConfig = field(
        default_factory=TelemetryMetricsConfig)
    recompile_detection: bool = C.TELEMETRY_RECOMPILE_DEFAULT
    # Goodput accounting (telemetry/goodput.py): wall-clock attribution,
    # engine/mfu and per-attempt run manifests. Pure host clock reads —
    # no device syncs even when on — so it defaults on with telemetry.
    goodput: bool = C.TELEMETRY_GOODPUT_DEFAULT
    # Fleet observability (telemetry/fleet.py): cross-host aggregation +
    # straggler detection. Opt-in (adds a per-flush collective).
    fleet: TelemetryFleetConfig = field(default_factory=TelemetryFleetConfig)
    # Memory observatory (telemetry/memory.py): XLA attribution, ledger,
    # capacity planner, OOM forensics. Opt-in (adds one AOT compile).
    memory: TelemetryMemoryConfig = field(
        default_factory=TelemetryMemoryConfig)
    # Device-time observatory (telemetry/devicetime.py): scheduled
    # jax.profiler captures -> measured op-level attribution, roofline,
    # measured exposed-comm. Opt-in (profiler work at capture boundaries).
    devicetime: TelemetryDevicetimeConfig = field(
        default_factory=TelemetryDevicetimeConfig)
    # Numerics observatory (telemetry/numerics.py): per-layer-group
    # grad/update stats + saturation counters + quantization-error
    # gauges. Opt-in (adds in-program stat reductions to the step).
    numerics: TelemetryNumericsConfig = field(
        default_factory=TelemetryNumericsConfig)
    # Request observatory (telemetry/requests.py): per-request SLO
    # accounting + serving-time partition for the serve engine. Opt-in
    # (host clock arithmetic per step + one record per finished request).
    requests: TelemetryRequestsConfig = field(
        default_factory=TelemetryRequestsConfig)

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> "TelemetryConfig":
        d = d or {}
        cfg = cls(
            enabled=bool(_get(d, C.TELEMETRY_ENABLED, False)),
            dir=str(_get(d, C.TELEMETRY_DIR, C.TELEMETRY_DIR_DEFAULT)),
            trace=TelemetryTraceConfig.from_dict(d.get(C.TELEMETRY_TRACE)),
            metrics=TelemetryMetricsConfig.from_dict(
                d.get(C.TELEMETRY_METRICS)),
            recompile_detection=bool(_get(d, C.TELEMETRY_RECOMPILE,
                                          C.TELEMETRY_RECOMPILE_DEFAULT)),
            goodput=bool(_get(d, C.TELEMETRY_GOODPUT,
                              C.TELEMETRY_GOODPUT_DEFAULT)),
            fleet=TelemetryFleetConfig.from_dict(d.get(C.TELEMETRY_FLEET)),
            memory=TelemetryMemoryConfig.from_dict(
                d.get(C.TELEMETRY_MEMORY)),
            devicetime=TelemetryDevicetimeConfig.from_dict(
                d.get(C.TELEMETRY_DEVICETIME)),
            numerics=TelemetryNumericsConfig.from_dict(
                d.get(C.TELEMETRY_NUMERICS)),
            requests=TelemetryRequestsConfig.from_dict(
                d.get(C.TELEMETRY_REQUESTS)),
        )
        if cfg.enabled and not cfg.dir:
            raise ConfigError(
                "telemetry.enabled requires telemetry.dir (where the trace "
                "file and metrics JSONL land)")
        if cfg.fleet.enabled and not cfg.goodput:
            raise ConfigError(
                "telemetry.fleet requires telemetry.goodput (fleet "
                "aggregation reads the goodput accountant's deltas)")
        if cfg.devicetime.enabled and cfg.trace.jax_profiler_dir:
            raise ConfigError(
                "telemetry.devicetime and telemetry.trace.jax_profiler_dir "
                "are mutually exclusive: the passthrough holds THE one "
                "jax.profiler session open for the whole run, so scheduled "
                "captures could never start")
        return cfg


@dataclass
class ServingConfig:
    """``serving`` block — the continuous-batching serving engine
    (serving/engine.py, docs/SERVING.md).

    ``max_batch_size``: decode slots (the static batch width of the one
    compiled decode program). ``kv_block_size`` / ``kv_num_blocks``: the
    paged KV pool geometry — capacity is ``(kv_num_blocks - 1) *
    kv_block_size`` cache positions (block 0 is reserved scratch).
    ``int8_kv_cache``: store KV as blockwise int8 + per-(token, head)
    fp32 scales (comm/quantize.py RTNE). ``max_model_len``: per-sequence
    prompt+output cap (defaults to the model's max_seq_len).
    ``max_prefills_per_step``: prefills admitted per decode boundary —
    bounds how long the decode batch waits on prompt processing.
    ``temperature``/``top_k``/``seed``: engine-wide sampling policy
    (0.0 = greedy, byte-reproducible).

    Decode fast path (docs/SERVING.md "Decode fast path" — all three
    off by default, PR-8 bit-identical): ``decode_attention``
    gather|auto|kernel selects the Pallas paged decode-attention kernel
    (with the max-active-length-capped gather as its fallback);
    ``prefix_cache`` turns on COW prompt-head block reuse;
    ``speculative`` configures draft-model speculative decoding
    (greedy-identical by construction — requires ``temperature == 0``).

    Resilience (docs/SERVING.md "Serving under failure" — off by
    default, zero-overhead): the ``resilience`` sub-block turns on
    per-request deadlines + ``cancel()``, the SLO-aware admission gate
    (``max_queue_wait_ms`` projected-wait shed, ``max_queue_depth``
    hard backstop), decode-dispatch retry/rebuild/replay recovery
    (``max_retries`` / ``retry_base_sec``) and the degradation ladder
    (``degrade_after`` anomalies per rung; ``slow_step_ms`` marks a
    decode step as an anomaly).

    Chunked prefill (docs/SERVING.md "Chunked prefill admission" — off
    by default, zero-overhead): the ``chunked_prefill`` sub-block
    switches admission to Sarathi-style mixed steps — decode tokens plus
    prefill chunks of admitted prompts share ONE ragged program, bounded
    by ``token_budget`` tokens per step (requires ``temperature == 0``).
    """

    max_batch_size: int = C.SERVING_MAX_BATCH_SIZE_DEFAULT
    kv_block_size: int = C.SERVING_KV_BLOCK_SIZE_DEFAULT
    kv_num_blocks: int = C.SERVING_KV_NUM_BLOCKS_DEFAULT
    int8_kv_cache: bool = C.SERVING_INT8_KV_CACHE_DEFAULT
    max_model_len: Optional[int] = None
    max_prefills_per_step: int = C.SERVING_MAX_PREFILLS_PER_STEP_DEFAULT
    eos_token_id: Optional[int] = None
    temperature: float = C.SERVING_TEMPERATURE_DEFAULT
    top_k: int = C.SERVING_TOP_K_DEFAULT
    seed: int = C.SERVING_SEED_DEFAULT
    decode_attention: str = C.SERVING_DECODE_ATTENTION_DEFAULT
    prefix_cache: bool = C.SERVING_PREFIX_CACHE_DEFAULT
    spec_decode: bool = C.SERVING_SPEC_ENABLED_DEFAULT
    spec_k: int = C.SERVING_SPEC_K_DEFAULT
    spec_draft_layers: Optional[int] = None
    resilience: bool = C.SERVING_RESIL_ENABLED_DEFAULT
    resil_max_queue_depth: Optional[int] = None
    resil_max_queue_wait_ms: Optional[float] = None
    resil_default_deadline_ms: Optional[float] = None
    resil_max_retries: int = C.SERVING_RESIL_MAX_RETRIES_DEFAULT
    resil_retry_base_sec: float = C.SERVING_RESIL_RETRY_BASE_SEC_DEFAULT
    resil_degrade_after: int = C.SERVING_RESIL_DEGRADE_AFTER_DEFAULT
    resil_slow_step_ms: Optional[float] = None
    chunked_prefill: bool = C.SERVING_CHUNKED_ENABLED_DEFAULT
    chunked_token_budget: int = C.SERVING_CHUNKED_TOKEN_BUDGET_DEFAULT

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> "ServingConfig":
        d = d or {}
        cfg = cls(
            max_batch_size=int(_get(d, C.SERVING_MAX_BATCH_SIZE,
                                    C.SERVING_MAX_BATCH_SIZE_DEFAULT)),
            kv_block_size=int(_get(d, C.SERVING_KV_BLOCK_SIZE,
                                   C.SERVING_KV_BLOCK_SIZE_DEFAULT)),
            kv_num_blocks=int(_get(d, C.SERVING_KV_NUM_BLOCKS,
                                   C.SERVING_KV_NUM_BLOCKS_DEFAULT)),
            int8_kv_cache=bool(_get(d, C.SERVING_INT8_KV_CACHE,
                                    C.SERVING_INT8_KV_CACHE_DEFAULT)),
            max_model_len=(int(d[C.SERVING_MAX_MODEL_LEN])
                           if d.get(C.SERVING_MAX_MODEL_LEN) is not None
                           else None),
            max_prefills_per_step=int(_get(
                d, C.SERVING_MAX_PREFILLS_PER_STEP,
                C.SERVING_MAX_PREFILLS_PER_STEP_DEFAULT)),
            eos_token_id=(int(d[C.SERVING_EOS_TOKEN_ID])
                          if d.get(C.SERVING_EOS_TOKEN_ID) is not None
                          else None),
            temperature=float(_get(d, C.SERVING_TEMPERATURE,
                                   C.SERVING_TEMPERATURE_DEFAULT)),
            top_k=int(_get(d, C.SERVING_TOP_K, C.SERVING_TOP_K_DEFAULT)),
            seed=int(_get(d, C.SERVING_SEED, C.SERVING_SEED_DEFAULT)),
            decode_attention=str(_get(
                d, C.SERVING_DECODE_ATTENTION,
                C.SERVING_DECODE_ATTENTION_DEFAULT)),
            prefix_cache=bool(_get(d, C.SERVING_PREFIX_CACHE,
                                   C.SERVING_PREFIX_CACHE_DEFAULT)),
        )
        spec = d.get(C.SERVING_SPECULATIVE) or {}
        if not isinstance(spec, dict):
            raise ConfigError("serving.speculative must be a dict")
        cfg.spec_decode = bool(spec.get(C.SERVING_SPEC_ENABLED,
                                        C.SERVING_SPEC_ENABLED_DEFAULT))
        cfg.spec_k = int(spec.get(C.SERVING_SPEC_K,
                                  C.SERVING_SPEC_K_DEFAULT))
        cfg.spec_draft_layers = (
            int(spec[C.SERVING_SPEC_DRAFT_LAYERS])
            if spec.get(C.SERVING_SPEC_DRAFT_LAYERS) is not None else None)
        resil = d.get(C.SERVING_RESILIENCE) or {}
        if not isinstance(resil, dict):
            raise ConfigError("serving.resilience must be a dict")
        # a present block defaults to enabled (like `moe`): writing
        # `resilience: {}` is an opt-in, `enabled: false` keeps it inert
        cfg.resilience = bool(resil.get(C.SERVING_RESIL_ENABLED,
                                        bool(resil) or
                                        C.SERVING_RESIL_ENABLED_DEFAULT))
        cfg.resil_max_queue_depth = (
            int(resil[C.SERVING_RESIL_MAX_QUEUE_DEPTH])
            if resil.get(C.SERVING_RESIL_MAX_QUEUE_DEPTH) is not None
            else None)
        cfg.resil_max_queue_wait_ms = (
            float(resil[C.SERVING_RESIL_MAX_QUEUE_WAIT_MS])
            if resil.get(C.SERVING_RESIL_MAX_QUEUE_WAIT_MS) is not None
            else None)
        cfg.resil_default_deadline_ms = (
            float(resil[C.SERVING_RESIL_DEFAULT_DEADLINE_MS])
            if resil.get(C.SERVING_RESIL_DEFAULT_DEADLINE_MS) is not None
            else None)
        cfg.resil_max_retries = int(resil.get(
            C.SERVING_RESIL_MAX_RETRIES,
            C.SERVING_RESIL_MAX_RETRIES_DEFAULT))
        cfg.resil_retry_base_sec = float(resil.get(
            C.SERVING_RESIL_RETRY_BASE_SEC,
            C.SERVING_RESIL_RETRY_BASE_SEC_DEFAULT))
        cfg.resil_degrade_after = int(resil.get(
            C.SERVING_RESIL_DEGRADE_AFTER,
            C.SERVING_RESIL_DEGRADE_AFTER_DEFAULT))
        cfg.resil_slow_step_ms = (
            float(resil[C.SERVING_RESIL_SLOW_STEP_MS])
            if resil.get(C.SERVING_RESIL_SLOW_STEP_MS) is not None
            else None)
        known_resil = {C.SERVING_RESIL_ENABLED,
                       C.SERVING_RESIL_MAX_QUEUE_DEPTH,
                       C.SERVING_RESIL_MAX_QUEUE_WAIT_MS,
                       C.SERVING_RESIL_DEFAULT_DEADLINE_MS,
                       C.SERVING_RESIL_MAX_RETRIES,
                       C.SERVING_RESIL_RETRY_BASE_SEC,
                       C.SERVING_RESIL_DEGRADE_AFTER,
                       C.SERVING_RESIL_SLOW_STEP_MS}
        unknown = set(resil) - known_resil
        if unknown:
            raise ConfigError(
                f"unknown serving.resilience keys {sorted(unknown)}; "
                f"expected a subset of {sorted(known_resil)}")
        chunked = d.get(C.SERVING_CHUNKED_PREFILL)
        has_chunked = chunked is not None
        chunked = chunked or {}
        if not isinstance(chunked, dict):
            raise ConfigError("serving.chunked_prefill must be a dict")
        # a present block defaults to enabled (like `resilience`)
        cfg.chunked_prefill = bool(chunked.get(
            C.SERVING_CHUNKED_ENABLED,
            has_chunked or C.SERVING_CHUNKED_ENABLED_DEFAULT))
        cfg.chunked_token_budget = int(chunked.get(
            C.SERVING_CHUNKED_TOKEN_BUDGET,
            C.SERVING_CHUNKED_TOKEN_BUDGET_DEFAULT))
        known_chunked = {C.SERVING_CHUNKED_ENABLED,
                         C.SERVING_CHUNKED_TOKEN_BUDGET}
        unknown = set(chunked) - known_chunked
        if unknown:
            raise ConfigError(
                f"unknown serving.chunked_prefill keys {sorted(unknown)}; "
                f"expected a subset of {sorted(known_chunked)}")
        if cfg.max_batch_size < 1:
            raise ConfigError("serving.max_batch_size must be >= 1")
        if cfg.kv_block_size < 1:
            raise ConfigError("serving.kv_block_size must be >= 1")
        if cfg.kv_num_blocks < 2:
            raise ConfigError(
                "serving.kv_num_blocks must be >= 2 (block 0 is reserved "
                "as the scratch block for inactive slots)")
        if cfg.max_model_len is not None and cfg.max_model_len < 1:
            raise ConfigError("serving.max_model_len must be >= 1")
        if cfg.max_prefills_per_step < 1:
            raise ConfigError("serving.max_prefills_per_step must be >= 1")
        if cfg.temperature < 0:
            raise ConfigError("serving.temperature must be >= 0")
        if cfg.top_k < 0:
            raise ConfigError("serving.top_k must be >= 0")
        if cfg.decode_attention not in C.SERVING_DECODE_ATTENTION_CHOICES:
            raise ConfigError(
                f"serving.decode_attention must be one of "
                f"{C.SERVING_DECODE_ATTENTION_CHOICES}, got "
                f"{cfg.decode_attention!r}")
        if cfg.spec_k < 1:
            raise ConfigError("serving.speculative.k must be >= 1")
        if cfg.spec_draft_layers is not None and cfg.spec_draft_layers < 1:
            raise ConfigError(
                "serving.speculative.draft_layers must be >= 1")
        if cfg.spec_decode and cfg.temperature != 0.0:
            raise ConfigError(
                "serving.speculative requires temperature == 0 (greedy): "
                "the accept/rollback contract is token-identity with "
                "greedy decode")
        if cfg.resil_max_queue_depth is not None \
                and cfg.resil_max_queue_depth < 1:
            raise ConfigError(
                "serving.resilience.max_queue_depth must be >= 1")
        if cfg.resil_max_queue_wait_ms is not None \
                and cfg.resil_max_queue_wait_ms <= 0:
            raise ConfigError(
                "serving.resilience.max_queue_wait_ms must be > 0")
        if cfg.resil_default_deadline_ms is not None \
                and cfg.resil_default_deadline_ms <= 0:
            raise ConfigError(
                "serving.resilience.default_deadline_ms must be > 0")
        if cfg.resil_max_retries < 0:
            raise ConfigError("serving.resilience.max_retries must be >= 0")
        if cfg.resil_retry_base_sec <= 0:
            raise ConfigError(
                "serving.resilience.retry_base_sec must be > 0")
        if cfg.resil_degrade_after < 1:
            raise ConfigError(
                "serving.resilience.degrade_after must be >= 1")
        if cfg.resil_slow_step_ms is not None and cfg.resil_slow_step_ms <= 0:
            raise ConfigError(
                "serving.resilience.slow_step_ms must be > 0")
        if cfg.chunked_token_budget < cfg.max_batch_size:
            raise ConfigError(
                "serving.chunked_prefill.token_budget must be >= "
                "max_batch_size (every decoding slot needs a row in each "
                "mixed step)")
        if cfg.chunked_prefill and cfg.temperature != 0.0:
            raise ConfigError(
                "serving.chunked_prefill requires temperature == 0 "
                "(greedy): the mixed program samples every ragged row "
                "with one key, and the contract with the bucketed path "
                "is token identity")
        return cfg


def _int_tuple(v, name: str) -> tuple:
    if v is None:
        return ()
    if not isinstance(v, (list, tuple)):
        raise ConfigError(f"{name} must be a list, got {type(v).__name__}")
    return tuple(int(x) for x in v)


def _float_tuple(v, name: str) -> tuple:
    if v is None:
        return ()
    if not isinstance(v, (list, tuple)):
        raise ConfigError(f"{name} must be a list, got {type(v).__name__}")
    return tuple(float(x) for x in v)


def _str_tuple(v, name: str) -> tuple:
    if v is None:
        return ()
    if not isinstance(v, (list, tuple)):
        raise ConfigError(f"{name} must be a list, got {type(v).__name__}")
    return tuple(str(x).lower() for x in v)


@dataclass
class MoeConfig:
    """``moe`` block — expert-parallel MoE training (moe/; docs/MOE.md).

    When enabled, ``deepspeed_tpu.initialize(model=...)`` swaps the
    in-tree GPT family's FFN blocks for MoE layers (every
    ``layer_freq``-th block), pins the engine mesh into the layer so the
    ``alltoall`` dispatch path has its expert axis, and — with telemetry
    on — turns on the moe/* gauges and per-expert numerics groups. A
    present block defaults to enabled (set ``enabled: false`` to keep a
    block around inert). Absent/off is provably free: no surgery, no
    extra step outputs, bit-identical lowered train step
    (tests/test_moe.py pins it)."""

    enabled: bool = C.MOE_ENABLED_DEFAULT
    num_experts: int = C.MOE_NUM_EXPERTS_DEFAULT
    k: int = C.MOE_TOP_K_DEFAULT
    layer_freq: int = C.MOE_LAYER_FREQ_DEFAULT
    capacity_factor: float = C.MOE_CAPACITY_FACTOR_DEFAULT
    eval_capacity_factor: float = C.MOE_EVAL_CAPACITY_FACTOR_DEFAULT
    min_capacity: int = C.MOE_MIN_CAPACITY_DEFAULT
    aux_alpha: float = C.MOE_AUX_ALPHA_DEFAULT
    router_jitter: float = C.MOE_ROUTER_JITTER_DEFAULT
    dispatch: str = C.MOE_DISPATCH_DEFAULT

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> "MoeConfig":
        # an empty `moe: {}` block is still an opt-in (all defaults)
        present = d is not None
        d = d or {}
        cfg = cls(
            enabled=bool(_get(d, C.MOE_ENABLED, present)),
            num_experts=int(_get(d, C.MOE_NUM_EXPERTS,
                                 C.MOE_NUM_EXPERTS_DEFAULT)),
            k=int(_get(d, C.MOE_TOP_K, C.MOE_TOP_K_DEFAULT)),
            layer_freq=int(_get(d, C.MOE_LAYER_FREQ,
                                C.MOE_LAYER_FREQ_DEFAULT)),
            capacity_factor=float(_get(d, C.MOE_CAPACITY_FACTOR,
                                       C.MOE_CAPACITY_FACTOR_DEFAULT)),
            eval_capacity_factor=float(_get(
                d, C.MOE_EVAL_CAPACITY_FACTOR,
                C.MOE_EVAL_CAPACITY_FACTOR_DEFAULT)),
            min_capacity=int(_get(d, C.MOE_MIN_CAPACITY,
                                  C.MOE_MIN_CAPACITY_DEFAULT)),
            aux_alpha=float(_get(d, C.MOE_AUX_ALPHA,
                                 C.MOE_AUX_ALPHA_DEFAULT)),
            router_jitter=float(_get(d, C.MOE_ROUTER_JITTER,
                                     C.MOE_ROUTER_JITTER_DEFAULT)),
            dispatch=str(_get(d, C.MOE_DISPATCH,
                              C.MOE_DISPATCH_DEFAULT)).lower(),
        )
        if not cfg.enabled:
            return cfg
        if cfg.num_experts < 2:
            raise ConfigError(
                f"moe.num_experts must be >= 2, got {cfg.num_experts}")
        if cfg.k not in (1, 2):
            raise ConfigError(f"moe.k must be 1 or 2, got {cfg.k}")
        if cfg.layer_freq < 1:
            raise ConfigError(
                f"moe.layer_freq must be >= 1, got {cfg.layer_freq}")
        if cfg.capacity_factor <= 0 or cfg.eval_capacity_factor <= 0:
            raise ConfigError(
                f"moe capacity factors must be positive, got "
                f"{cfg.capacity_factor}/{cfg.eval_capacity_factor}")
        if cfg.min_capacity < 1:
            raise ConfigError(
                f"moe.min_capacity must be >= 1, got {cfg.min_capacity}")
        if cfg.aux_alpha < 0:
            raise ConfigError(
                f"moe.aux_alpha must be >= 0, got {cfg.aux_alpha}")
        if not (0.0 <= cfg.router_jitter < 1.0):
            raise ConfigError(
                f"moe.router_jitter must be in [0, 1), got "
                f"{cfg.router_jitter}")
        if cfg.dispatch not in C.MOE_DISPATCH_CHOICES:
            raise ConfigError(
                f"moe.dispatch must be drawn from "
                f"{'/'.join(C.MOE_DISPATCH_CHOICES)}, got "
                f"'{cfg.dispatch}'")
        return cfg


@dataclass
class AutotuningConfig:
    """``autotuning`` block — the startup config search
    (autotuning/; docs/PERFORMANCE.md "Autotuning").

    Three stages: enumerate the knob space (every list here overrides the
    derived default axis), prune candidates that fail the ConfigError
    walls at parse or project over ``headroom_frac`` x HBM through the
    engine-free capacity projection (telemetry/memory.py), then run
    short in-process measured trials of the ``top_k``
    projected-fastest survivors (compile + ``trial_steps`` timed steps
    each, successive-halving early stop at ``halving_factor``) and adopt
    the measured winner. ``enabled`` gates only the automatic run inside
    ``deepspeed_tpu.initialize`` (and the launcher's ``--autotune`` env
    handshake); an explicit ``deepspeed_tpu.autotune(engine, ...)`` call
    reads the knobs regardless. Default OFF is provably free: no
    autotuning import at engine init, zero extra syncs, bit-identical
    lowered step."""

    enabled: bool = C.AUTOTUNING_ENABLED_DEFAULT
    zero_stages: tuple = ()
    micro_gas: tuple = ()            # ((micro, gas), ...) overrides
    bucket_mbs: tuple = ()
    dcn_quant_bits: tuple = ()
    overlap: tuple = ()              # overlap_grad_sync values
    zeropp: tuple = ()               # quantized_weights tiers
    moe_experts: tuple = ()          # expert counts (prune-only axis)
    moe_capacity_factors: tuple = ()
    moe_dispatch: tuple = ()         # einsum | scatter | alltoall
    top_k: int = C.AUTOTUNING_TOP_K_DEFAULT
    trial_steps: int = C.AUTOTUNING_TRIAL_STEPS_DEFAULT
    trial_warmup: int = C.AUTOTUNING_TRIAL_WARMUP_DEFAULT
    halving_factor: float = C.AUTOTUNING_HALVING_FACTOR_DEFAULT
    headroom_frac: float = C.AUTOTUNING_HEADROOM_FRAC_DEFAULT
    activation_bytes_per_sample: float = C.AUTOTUNING_ACT_BYTES_DEFAULT
    hbm_limit_gb: Optional[float] = None
    max_candidates: int = C.AUTOTUNING_MAX_CANDIDATES_DEFAULT
    result_file: str = C.AUTOTUNING_RESULT_FILE_DEFAULT

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> "AutotuningConfig":
        d = d or {}
        if C.AUTOTUNING_ENABLED in d and d[C.AUTOTUNING_ENABLED] is not None:
            # An explicit value always wins — the tuner's own candidate
            # configs carry `enabled: false` precisely so a candidate
            # (the adopted one included) can never recursively search.
            enabled = bool(d[C.AUTOTUNING_ENABLED])
        else:
            # Launcher handshake: `dstpu --autotune` exports the env so
            # unmodified scripts (no explicit key) enable the search
            # through their config parse.
            enabled = (C.AUTOTUNING_ENABLED_DEFAULT
                       or os.environ.get(C.AUTOTUNING_ENV, "")
                       not in ("", "0"))
        mg = d.get(C.AUTOTUNING_MICRO_GAS)
        micro_gas = ()
        if mg is not None:
            if not isinstance(mg, (list, tuple)) or not all(
                    isinstance(p, (list, tuple)) and len(p) == 2
                    for p in mg):
                raise ConfigError(
                    "autotuning.micro_gas must be a list of [micro, gas] "
                    f"pairs, got {mg!r}")
            micro_gas = tuple((int(m), int(g)) for m, g in mg)
        cfg = cls(
            enabled=enabled,
            zero_stages=_int_tuple(d.get(C.AUTOTUNING_ZERO_STAGES),
                                   "autotuning.zero_stages"),
            micro_gas=micro_gas,
            bucket_mbs=_float_tuple(d.get(C.AUTOTUNING_BUCKET_MBS),
                                    "autotuning.bucket_mbs"),
            dcn_quant_bits=_int_tuple(d.get(C.AUTOTUNING_DCN_QUANT_BITS),
                                      "autotuning.dcn_quant_bits"),
            overlap=_str_tuple(d.get(C.AUTOTUNING_OVERLAP),
                               "autotuning.overlap"),
            zeropp=_str_tuple(d.get(C.AUTOTUNING_ZEROPP),
                              "autotuning.zeropp"),
            moe_experts=_int_tuple(d.get(C.AUTOTUNING_MOE_EXPERTS),
                                   "autotuning.moe_experts"),
            moe_capacity_factors=_float_tuple(
                d.get(C.AUTOTUNING_MOE_CAPACITY_FACTORS),
                "autotuning.moe_capacity_factors"),
            moe_dispatch=_str_tuple(d.get(C.AUTOTUNING_MOE_DISPATCH),
                                    "autotuning.moe_dispatch"),
            top_k=int(_get(d, C.AUTOTUNING_TOP_K,
                           C.AUTOTUNING_TOP_K_DEFAULT)),
            trial_steps=int(_get(d, C.AUTOTUNING_TRIAL_STEPS,
                                 C.AUTOTUNING_TRIAL_STEPS_DEFAULT)),
            trial_warmup=int(_get(d, C.AUTOTUNING_TRIAL_WARMUP,
                                  C.AUTOTUNING_TRIAL_WARMUP_DEFAULT)),
            halving_factor=float(_get(d, C.AUTOTUNING_HALVING_FACTOR,
                                      C.AUTOTUNING_HALVING_FACTOR_DEFAULT)),
            headroom_frac=float(_get(d, C.AUTOTUNING_HEADROOM_FRAC,
                                     C.AUTOTUNING_HEADROOM_FRAC_DEFAULT)),
            activation_bytes_per_sample=float(_get(
                d, C.AUTOTUNING_ACT_BYTES, C.AUTOTUNING_ACT_BYTES_DEFAULT)),
            hbm_limit_gb=(float(d[C.AUTOTUNING_HBM_LIMIT_GB])
                          if d.get(C.AUTOTUNING_HBM_LIMIT_GB) is not None
                          else None),
            max_candidates=int(_get(d, C.AUTOTUNING_MAX_CANDIDATES,
                                    C.AUTOTUNING_MAX_CANDIDATES_DEFAULT)),
            result_file=str(_get(d, C.AUTOTUNING_RESULT_FILE,
                                 C.AUTOTUNING_RESULT_FILE_DEFAULT)),
        )
        if cfg.top_k < 1:
            raise ConfigError(
                f"autotuning.top_k must be >= 1, got {cfg.top_k}")
        if cfg.trial_steps < 1:
            raise ConfigError(
                f"autotuning.trial_steps must be >= 1, got "
                f"{cfg.trial_steps}")
        if cfg.trial_warmup < 0:
            raise ConfigError(
                f"autotuning.trial_warmup must be >= 0, got "
                f"{cfg.trial_warmup}")
        if cfg.halving_factor <= 1.0:
            raise ConfigError(
                f"autotuning.halving_factor must be > 1 (a factor <= 1 "
                f"would eliminate every candidate including the best), "
                f"got {cfg.halving_factor}")
        if not (0.0 < cfg.headroom_frac <= 1.0):
            raise ConfigError(
                f"autotuning.headroom_frac must be in (0, 1], got "
                f"{cfg.headroom_frac}")
        if cfg.hbm_limit_gb is not None and cfg.hbm_limit_gb <= 0:
            raise ConfigError(
                f"autotuning.hbm_limit_gb must be positive, got "
                f"{cfg.hbm_limit_gb}")
        if cfg.max_candidates < 1:
            raise ConfigError(
                f"autotuning.max_candidates must be >= 1, got "
                f"{cfg.max_candidates}")
        bad = [s for s in cfg.zero_stages if s not in (0, 1, 2, 3)]
        if bad:
            raise ConfigError(
                f"autotuning.zero_stages must be drawn from 0-3, got {bad}")
        bad = [b for b in cfg.dcn_quant_bits if b not in (8, 16, 32)]
        if bad:
            raise ConfigError(
                f"autotuning.dcn_quant_bits must be drawn from 8/16/32, "
                f"got {bad}")
        bad = [o for o in cfg.overlap if o not in ("auto", "on", "off")]
        if bad:
            raise ConfigError(
                f"autotuning.overlap must be drawn from auto/on/off, "
                f"got {bad}")
        bad = [z for z in cfg.zeropp if z not in ("off", "bf16", "int8")]
        if bad:
            raise ConfigError(
                f"autotuning.zeropp must be drawn from off/bf16/int8, "
                f"got {bad}")
        bad = [e for e in cfg.moe_experts if e < 2]
        if bad:
            raise ConfigError(
                f"autotuning.moe_experts must be >= 2, got {bad}")
        bad = [f for f in cfg.moe_capacity_factors if f <= 0]
        if bad:
            raise ConfigError(
                f"autotuning.moe_capacity_factors must be positive, "
                f"got {bad}")
        bad = [m for m in cfg.moe_dispatch
               if m not in C.MOE_DISPATCH_CHOICES]
        if bad:
            raise ConfigError(
                f"autotuning.moe_dispatch must be drawn from "
                f"{'/'.join(C.MOE_DISPATCH_CHOICES)}, got {bad}")
        if any(m < 1 or g < 1 for m, g in cfg.micro_gas):
            raise ConfigError(
                f"autotuning.micro_gas pairs must be positive, got "
                f"{cfg.micro_gas}")
        # The result file is discovered by pattern by the stdlib-only
        # autotune_report (same argument as memory.plan_file).
        if not (cfg.result_file.startswith("autotune_result")
                and cfg.result_file.endswith(".json")):
            raise ConfigError(
                "autotuning.result_file must match 'autotune_result*.json' "
                f"(tools/autotune_report.py discovers it by that pattern), "
                f"got '{cfg.result_file}'")
        return cfg


@dataclass
class TensorboardConfig:
    enabled: bool = False
    output_path: str = ""
    job_name: str = "DeepSpeedTPUJob"

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> "TensorboardConfig":
        d = d or {}
        return cls(enabled=bool(_get(d, C.TENSORBOARD_ENABLED, False)),
                   output_path=str(_get(d, C.TENSORBOARD_OUTPUT_PATH, "")),
                   job_name=str(_get(d, C.TENSORBOARD_JOB_NAME, "DeepSpeedTPUJob")))


class DeepSpeedTPUConfig:
    """Parsed, validated, fully-resolved training configuration."""

    def __init__(self,
                 config: Union[str, Dict[str, Any], None],
                 world_size: Optional[int] = None):
        if config is None:
            config = {}
        if isinstance(config, str):
            if not os.path.exists(config):
                raise ConfigError(f"config file not found: {config}")
            with open(config, "r") as f:
                self._param_dict = json.load(f)
        elif isinstance(config, dict):
            self._param_dict = dict(config)
        else:
            raise ConfigError(f"config must be a path or dict, got {type(config)}")

        d = self._param_dict
        self.world_size = int(world_size) if world_size is not None else self._default_world()

        # --- mesh / parallel shape -------------------------------------------------
        self.mesh = MeshConfig.from_dict(d.get(C.MESH))
        self.data_parallel_size = self.mesh.resolve_data(self.world_size)

        # --- elasticity: takes control of the batch triple when enabled ------------
        # (reference runtime/config.py:679-733)
        self.elasticity = dict(d.get(C.ELASTICITY, {}))
        self.elasticity_enabled = bool(self.elasticity.get("enabled", False))
        # Live elasticity (resilience/elastic.py): in-process shrink/grow
        # + straggler eviction. Parsed here beside the ladder it rides;
        # compatibility walls live in _validate.
        self.elasticity_live = LiveElasticityConfig.from_dict(
            self.elasticity.get(C.ELASTICITY_LIVE))
        if self.elasticity_live.enabled:
            # Walled HERE, before the batch triple resolves: a live config
            # missing the ladder (or splitting the model over pipe) would
            # otherwise die on a misleading batch-math error instead of
            # the real cause. The remaining tier walls live in _validate.
            if not self.elasticity_enabled:
                raise ConfigError(
                    "elasticity.live requires the elastic batch ladder "
                    "(elasticity.enabled with max_train_batch_size/"
                    "micro_batch_sizes): the in-process world change picks "
                    "its new (world, micro, gas) from the ladder so the "
                    "global batch — and convergence — never changes")
            if (self.mesh.pipe > 1
                    or int(dict(d.get(C.PIPELINE, {})).get("stages", 1)) > 1):
                raise ConfigError(
                    "elasticity.live cannot compose with pipeline "
                    "parallelism: the pipe engine shards the MODEL over "
                    "the pipe axis — losing a slice loses layers, not "
                    "data-parallel replicas; use the plain engine")
        if self.elasticity_enabled:
            # The ladder solver must not see the live sub-block as an
            # unknown elasticity key (ElasticityConfig ignores extras, but
            # elastic_config_hash canonicalises only the batch-math keys —
            # live knobs are deliberately NOT convergence-relevant).
            self._apply_elasticity(d)

        # --- batch triple ----------------------------------------------------------
        micro = d.get(C.TRAIN_MICRO_BATCH_SIZE_PER_GPU,
                      d.get(C.TRAIN_MICRO_BATCH_SIZE_PER_CHIP))
        self.train_batch_size, self.train_micro_batch_size_per_gpu, \
            self.gradient_accumulation_steps = self._resolve_batch_triple(
                d.get(C.TRAIN_BATCH_SIZE), micro,
                d.get(C.GRADIENT_ACCUMULATION_STEPS), self.data_parallel_size)

        # --- optimizer / scheduler -------------------------------------------------
        opt = d.get(C.OPTIMIZER)
        self.optimizer_name: Optional[str] = None
        self.optimizer_params: Dict[str, Any] = {}
        self.optimizer_fused_update = C.OPTIMIZER_FUSED_UPDATE_DEFAULT
        if opt is not None:
            if C.OPTIMIZER_TYPE not in opt:
                raise ConfigError("optimizer block requires a 'type'")
            self.optimizer_name = str(opt[C.OPTIMIZER_TYPE]).lower()
            self.optimizer_params = dict(opt.get(C.OPTIMIZER_PARAMS, {}))
            self.optimizer_fused_update = bool(opt.get(
                C.OPTIMIZER_FUSED_UPDATE, C.OPTIMIZER_FUSED_UPDATE_DEFAULT))
        self.optimizer_legacy_fusion = bool(d.get("legacy_fusion", False))

        sched = d.get(C.SCHEDULER)
        self.scheduler_name: Optional[str] = None
        self.scheduler_params: Dict[str, Any] = {}
        if sched is not None:
            if C.SCHEDULER_TYPE not in sched:
                raise ConfigError("scheduler block requires a 'type'")
            self.scheduler_name = str(sched[C.SCHEDULER_TYPE])
            self.scheduler_params = dict(sched.get(C.SCHEDULER_PARAMS, {}))

        # --- precision -------------------------------------------------------------
        self.fp16 = FP16Config.from_dict(d.get(C.FP16))
        bf16_block = d.get(C.BF16, d.get(C.BFLOAT16))
        self.bf16_enabled = bool(_get(bf16_block or {}, C.BF16_ENABLED, False))
        if self.fp16.enabled and self.bf16_enabled:
            raise ConfigError("fp16 and bf16 cannot both be enabled")
        self.amp_enabled = bool(_get(d.get(C.AMP) or {}, C.AMP_ENABLED, False))
        self.gradient_clipping = float(_get(d, C.GRADIENT_CLIPPING,
                                            C.GRADIENT_CLIPPING_DEFAULT))
        self.prescale_gradients = bool(_get(d, C.PRESCALE_GRADIENTS,
                                            C.PRESCALE_GRADIENTS_DEFAULT))
        self.gradient_predivide_factor = float(_get(d, C.GRADIENT_PREDIVIDE_FACTOR,
                                                    C.GRADIENT_PREDIVIDE_FACTOR_DEFAULT))
        # communication_data_type: the ICI reduction dtype for the
        # grad-sync strategy (comm/grad_sync.py) and the 1-bit path's
        # dense intra-slice pre-reduction. None ≡ the accumulator's
        # native dtype.
        self.communication_data_type = d.get(C.COMMUNICATION_DATA_TYPE)
        if self.communication_data_type is not None:
            self.communication_data_type = \
                str(self.communication_data_type).lower()
            if self.communication_data_type not in (
                    "fp32", "float32", "bf16", "bfloat16", "fp16",
                    "float16"):
                raise ConfigError(
                    f"communication_data_type must be one of fp32/float32/"
                    f"bf16/bfloat16/fp16/float16, got "
                    f"'{self.communication_data_type}'")
        # data_types.grad_accum_dtype (later-DeepSpeed key): the GAS
        # accumulator's dtype. The reference's fp16 engine accumulates in
        # half precision the same way (fp16 flat buffers); fp32 stays the
        # safe default here.
        dt_block = d.get("data_types") or {}
        self.grad_accum_dtype = str(
            dt_block.get("grad_accum_dtype", "float32"))
        if self.grad_accum_dtype not in ("float32", "fp32", "bfloat16",
                                         "bf16"):
            raise ConfigError(
                f"data_types.grad_accum_dtype must be float32 or bfloat16, "
                f"got '{self.grad_accum_dtype}'")

        # --- subsystem blocks ------------------------------------------------------
        self.zero_config = ZeroConfig.from_dict(d.get(C.ZERO_OPTIMIZATION))
        self.zero_enabled = self.zero_config.enabled
        self.activation_checkpointing_provided = C.ACTIVATION_CHECKPOINTING in d
        self.activation_checkpointing = ActivationCheckpointingConfig.from_dict(
            d.get(C.ACTIVATION_CHECKPOINTING))
        self.flops_profiler = FlopsProfilerConfig.from_dict(d.get(C.FLOPS_PROFILER))
        self.pld = PLDConfig.from_dict(d.get(C.PROGRESSIVE_LAYER_DROP))
        self.aio = AIOConfig.from_dict(d.get(C.AIO))
        self.tensorboard = TensorboardConfig.from_dict(d.get(C.TENSORBOARD))
        self.telemetry = TelemetryConfig.from_dict(d.get(C.TELEMETRY))
        self.resilience = ResilienceConfig.from_dict(d.get(C.RESILIENCE))
        self.comm = CommConfig.from_dict(d.get(C.COMM))
        self.guardrails = GuardrailsConfig.from_dict(d.get(C.GUARDRAILS))
        self.serving = ServingConfig.from_dict(d.get(C.SERVING))
        self.autotuning = AutotuningConfig.from_dict(d.get(C.AUTOTUNING))
        self.moe = MoeConfig.from_dict(d.get(C.MOE))
        self.sparse_attention = d.get(C.SPARSE_ATTENTION)
        self.pipeline = dict(d.get(C.PIPELINE, {}))
        self.eigenvalue = dict(d.get(C.EIGENVALUE, {}))
        self.quantize_training = dict(d.get(C.QUANTIZE_TRAINING, {}))

        # --- misc ------------------------------------------------------------------
        self.steps_per_print = int(_get(d, C.STEPS_PER_PRINT, C.STEPS_PER_PRINT_DEFAULT))
        self.wall_clock_breakdown = bool(_get(d, C.WALL_CLOCK_BREAKDOWN,
                                              C.WALL_CLOCK_BREAKDOWN_DEFAULT))
        self.memory_breakdown = bool(_get(d, C.MEMORY_BREAKDOWN,
                                          C.MEMORY_BREAKDOWN_DEFAULT))
        self.dump_state = bool(_get(d, C.DUMP_STATE, C.DUMP_STATE_DEFAULT))
        # Numerics debug mode (SURVEY §5's determinism/debug lever): every
        # train_batch verifies loss and params are finite (one host sync
        # per step — a DEBUG tool) and raises naming the step + leaves.
        self.check_numerics = bool(_get(d, C.CHECK_NUMERICS,
                                        C.CHECK_NUMERICS_DEFAULT))
        self.sparse_gradients_enabled = bool(_get(d, C.SPARSE_GRADIENTS,
                                                  C.SPARSE_GRADIENTS_DEFAULT))

        self._validate()

    # ------------------------------------------------------------------
    def _apply_elasticity(self, d: Dict[str, Any]) -> None:
        """Let the elastic config own the batch triple (reference
        runtime/config.py:679-733): compute (batch, micro, gas) for the
        current world size and write them into the param dict."""
        from deepspeed_tpu.elasticity import (ElasticityConfigError,
                                              compute_elastic_config,
                                              ensure_immutable_elastic_config)
        from deepspeed_tpu.utils.logging import logger
        from deepspeed_tpu.version import __version__

        final_batch, valid, micro = compute_elastic_config(
            d, __version__, world_size=self.world_size)
        ensure_immutable_elastic_config(self.elasticity)
        batch_keys = (C.TRAIN_BATCH_SIZE, C.TRAIN_MICRO_BATCH_SIZE_PER_GPU,
                      C.TRAIN_MICRO_BATCH_SIZE_PER_CHIP,
                      C.GRADIENT_ACCUMULATION_STEPS)
        if not self.elasticity.get("ignore_non_elastic_batch_info", False):
            if any(k in d for k in batch_keys):
                raise ElasticityConfigError(
                    "batch parameters found in config but elastic training "
                    "controls them; set "
                    "'ignore_non_elastic_batch_info': true to silence")
        gas = final_batch // (micro * self.world_size)
        logger.info("[Elasticity] batch=%d micro=%d gas=%d valid chip "
                    "counts: %s", final_batch, micro, gas, valid)
        d[C.TRAIN_BATCH_SIZE] = final_batch
        d[C.TRAIN_MICRO_BATCH_SIZE_PER_GPU] = micro
        d[C.GRADIENT_ACCUMULATION_STEPS] = gas
        self.elastic_valid_world_sizes = valid

    @staticmethod
    def _default_world() -> int:
        try:
            import jax

            return jax.device_count()
        except Exception:
            return 1

    @staticmethod
    def _resolve_batch_triple(train: Optional[int], micro: Optional[int],
                              gas: Optional[int], dp: int):
        """Solve/validate train = micro × gas × dp (reference config.py:822-893)."""
        if train is not None:
            train = int(train)
        if micro is not None:
            micro = int(micro)
        if gas is not None:
            gas = int(gas)

        if all(v is not None for v in (train, micro, gas)):
            if train != micro * gas * dp:
                raise ConfigError(
                    f"batch sizes inconsistent: train_batch_size={train} != "
                    f"micro({micro}) × gas({gas}) × dp({dp})")
        elif train is not None and micro is not None:
            if train % (micro * dp) != 0:
                raise ConfigError(
                    f"train_batch_size {train} not divisible by micro×dp={micro * dp}")
            gas = train // (micro * dp)
        elif train is not None and gas is not None:
            if train % (gas * dp) != 0:
                raise ConfigError(
                    f"train_batch_size {train} not divisible by gas×dp={gas * dp}")
            micro = train // (gas * dp)
        elif micro is not None:
            gas = gas or 1
            train = micro * gas * dp
        elif train is not None:
            gas = 1
            if train % dp != 0:
                raise ConfigError(f"train_batch_size {train} not divisible by dp={dp}")
            micro = train // dp
        else:
            raise ConfigError(
                "at least one of train_batch_size / train_micro_batch_size_per_gpu "
                "must be specified")
        for name, v in (("train_batch_size", train),
                        ("train_micro_batch_size_per_gpu", micro),
                        ("gradient_accumulation_steps", gas)):
            if v <= 0:
                raise ConfigError(f"{name} must be positive, got {v}")
        return train, micro, gas

    def _validate(self) -> None:
        if self.zero_config.stage >= 2 and self.pipeline.get("stages", self.mesh.pipe) > 1 \
                and self.mesh.pipe > 1:
            raise ConfigError("ZeRO stage >= 2 is incompatible with pipeline parallelism; "
                              "use stage 1 (reference pipe/engine.py:56)")
        if self.fp16.enabled and self.amp_enabled:
            raise ConfigError("fp16 and amp cannot both be enabled")
        if self.zero_config.zeropp.active:
            # Validated HERE (not only in the engine) so the user-level
            # initialize(model=..., offload_param=...) path fails with
            # the real cause instead of crashing in the offload-tier
            # model conversion it runs before engine construction.
            if self.zero_config.offload_param.enabled:
                raise ConfigError(
                    "zero_optimization.zeropp cannot compose with "
                    "offload_param: the hpZ secondary replica lives in "
                    "HBM while the offloaded primary partition lives in "
                    "host memory — the explicit quantized param gather "
                    "is a mesh collective, not a host fetch; drop "
                    "offload_param or disable zeropp")
            if self.zero_config.offload_optimizer.enabled:
                raise ConfigError(
                    "zero_optimization.zeropp cannot compose with "
                    "offload_optimizer: the offload tier's params reach "
                    "the device by host transfer, not a mesh all-gather "
                    "— there is no wire hop for qwZ to quantize; use a "
                    "device-resident optimizer tier")
        if self.elasticity_live.enabled:
            # Live elasticity rebuilds the mesh + step functions in-process
            # from gathered host state; the tiers below own their own state
            # layout or wire protocol and cannot be resharded behind their
            # backs — fail at parse with the real cause. (The ladder and
            # pipeline walls fire earlier, in __init__, before the batch
            # triple can mask them.)
            if self.zero_config.zeropp.active:
                raise ConfigError(
                    "elasticity.live cannot compose with "
                    "zero_optimization.zeropp yet: the explicit param "
                    "gather plan bakes the mesh into its wire layout — "
                    "drop zeropp or disable elasticity.live")
            if (self.zero_config.offload_param.enabled
                    or self.zero_config.offload_optimizer.enabled):
                raise ConfigError(
                    "elasticity.live cannot compose with the offload "
                    "tiers: host-resident master/param state is laid out "
                    "per-partition and the in-process reshard path "
                    "(install_state_arrays) only re-places device state")
            if str(self.optimizer_name or "").startswith("onebit"):
                raise ConfigError(
                    "elasticity.live cannot compose with 1-bit "
                    "optimizers: the error-compensated compressed-"
                    "momentum buffers are rank-local and do not survive "
                    "a world change")
        if self.autotuning.enabled:
            # The tuner's measured trials swap configs in-process through
            # the fused data-parallel tiers' _elastic_rebuild path; the
            # tiers below own their own state layout or wire protocol and
            # cannot be rebuilt behind their backs — same walls (and the
            # same reasons) as elasticity.live. The host-IMPLIED optimizer
            # tier (optimizer.type "cpuadam") resolves only at engine
            # level; deepspeed_tpu.autotune() re-checks it there.
            if (self.mesh.pipe > 1
                    or int(self.pipeline.get("stages", 1)) > 1):
                raise ConfigError(
                    "autotuning cannot compose with pipeline parallelism: "
                    "the pipe engine compiles its own schedule and the "
                    "in-process trial rebuild only re-places the fused "
                    "data-parallel tiers")
            if (self.zero_config.offload_param.enabled
                    or self.zero_config.offload_optimizer.enabled):
                raise ConfigError(
                    "autotuning cannot compose with the offload tiers: "
                    "host-resident master/param state is laid out per-"
                    "partition and the in-process trial rebuild "
                    "(install_state_arrays) only re-places device state")
            if str(self.optimizer_name or "").startswith("onebit"):
                raise ConfigError(
                    "autotuning cannot compose with 1-bit optimizers: the "
                    "error-compensated compressed-momentum buffers are "
                    "rank-local and do not survive a trial rebuild")
        if self.moe.enabled:
            # Expert-parallel composition walls (docs/MOE.md): the tiers
            # below own a state layout or program the expert-axis-sharded
            # stacked params cannot ride — fail at parse with the real
            # cause. These walls are also what makes the moe autotuner
            # axes prune invalid combos for free.
            if self.moe.num_experts % max(self.mesh.expert, 1) != 0:
                raise ConfigError(
                    f"moe.num_experts ({self.moe.num_experts}) must "
                    f"divide by the mesh expert axis "
                    f"({self.mesh.expert}): experts are one stacked "
                    f"leaf sharded over that axis")
            if (self.mesh.pipe > 1
                    or int(self.pipeline.get("stages", 1)) > 1):
                raise ConfigError(
                    "moe cannot compose with pipeline parallelism: the "
                    "pipe engine stacks its blocks into one scanned "
                    "program — a per-layer FFN/MoE swap breaks the "
                    "homogeneous stack; use the fused data-parallel "
                    "engine")
            if (self.zero_config.offload_param.enabled
                    or self.zero_config.offload_optimizer.enabled):
                raise ConfigError(
                    "moe cannot compose with the offload tiers: the "
                    "host-resident master partition is laid out over "
                    "(data,) flat shards and the expert-axis-sharded "
                    "stacked params do not fit it")
            if str(self.optimizer_name or "").startswith("onebit"):
                raise ConfigError(
                    "moe cannot compose with 1-bit optimizers: the "
                    "error-feedback buffers assume the (data,)-only "
                    "grad bucket layout, which expert-axis-sharded "
                    "grads break")
        if (self.telemetry.memory.enabled and self.guardrails.watchdog.enabled
                and self.telemetry.memory.oom_exit_code
                == self.guardrails.watchdog.exit_code):
            # The supervisor maps the watchdog rc to an IMMEDIATE restart
            # and the OOM rc to NO restart — one rc cannot mean both, and
            # the collision would hot-loop every deterministic OOM.
            raise ConfigError(
                f"telemetry.memory.oom_exit_code "
                f"({self.telemetry.memory.oom_exit_code}) collides with "
                f"guardrails.watchdog.exit_code — the supervisor restarts "
                f"watchdog exits immediately but must NOT restart OOM "
                f"exits; pick distinct codes")

    # convenience accessors mirroring the reference's getters ------------------
    @property
    def precision_dtype(self) -> str:
        if self.bf16_enabled:
            return "bfloat16"
        if self.fp16.enabled:
            return "float16"
        return "float32"

    @property
    def loss_scale(self) -> float:
        return self.fp16.loss_scale if self.fp16.enabled else 1.0

    @property
    def dynamic_loss_scale(self) -> bool:
        return self.fp16.enabled and self.fp16.dynamic_loss_scale

    def print_config(self) -> None:
        from deepspeed_tpu.utils.logging import logger

        logger.info("DeepSpeedTPUConfig:")
        logger.info(json.dumps(self._param_dict, indent=2, sort_keys=True, default=str))
