"""Per-host launcher (reference ``deepspeed/launcher/launch.py:67``).

The reference spawns one subprocess per local GPU with RANK/LOCAL_RANK env
and babysits them (kill-all on first failure, :151-167). A TPU host runs
ONE worker process that owns all local chips; this launcher therefore
decodes the world info, exports the jax.distributed rendezvous variables
(DSTPU_COORDINATOR / DSTPU_NUM_PROCS / DSTPU_RANK, consumed by
``parallel.mesh.init_distributed``) and execs the user script, babysitting
it for signal-forwarding parity.
"""

import argparse
import os
import signal
import subprocess
import sys

from deepspeed_tpu.launcher.runner import decode_world_info
from deepspeed_tpu.utils.logging import logger


def parse_args(args=None):
    parser = argparse.ArgumentParser(description="per-host launcher")
    parser.add_argument("--world_info", type=str, required=True)
    parser.add_argument("--node_rank", type=int, required=True)
    parser.add_argument("--master_addr", type=str, required=True)
    parser.add_argument("--master_port", type=int, default=29500)
    parser.add_argument("user_script", type=str)
    parser.add_argument("user_args", nargs=argparse.REMAINDER)
    return parser.parse_args(args)


def build_env(world_info: str, node_rank: int, master_addr: str,
              master_port: int) -> dict:
    world = decode_world_info(world_info)
    hosts = list(world.keys())
    if not 0 <= node_rank < len(hosts):
        raise ValueError(f"node_rank {node_rank} out of range for "
                         f"{len(hosts)} hosts")
    env = dict(os.environ)
    env.update({
        "DSTPU_COORDINATOR": f"{master_addr}:{master_port}",
        "DSTPU_NUM_PROCS": str(len(hosts)),
        "DSTPU_RANK": str(node_rank),
        # reference-compatible aliases
        "MASTER_ADDR": master_addr,
        "MASTER_PORT": str(master_port),
        "RANK": str(node_rank),
        "WORLD_SIZE": str(len(hosts)),
        "LOCAL_RANK": "0",
    })
    return env


def mpi_rank() -> int:
    """node_rank from the MPI environment (mpirun backends pass -1)."""
    for var in ("OMPI_COMM_WORLD_RANK", "PMI_RANK", "PMIX_RANK"):
        if var in os.environ:
            return int(os.environ[var])
    raise RuntimeError("--node_rank=-1 requires an MPI environment "
                       "(OMPI_COMM_WORLD_RANK / PMI_RANK not set)")


def main(args=None):
    args = parse_args(args)
    node_rank = args.node_rank if args.node_rank >= 0 else mpi_rank()
    env = build_env(args.world_info, node_rank, args.master_addr,
                    args.master_port)
    cmd = [sys.executable, args.user_script] + list(args.user_args)
    logger.info("node %s exec: %s", args.node_rank, " ".join(cmd))
    proc = subprocess.Popen(cmd, env=env)

    def forward(signum, _frame):
        proc.send_signal(signum)

    signal.signal(signal.SIGINT, forward)
    signal.signal(signal.SIGTERM, forward)
    rc = proc.wait()
    if rc != 0:
        logger.error("worker exited with code %s — terminating", rc)
    sys.exit(rc)


if __name__ == "__main__":
    main()
