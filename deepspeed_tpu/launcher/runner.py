"""Multi-node launch front-end — the ``deepspeed`` CLI (reference
``deepspeed/launcher/runner.py:33-372``).

Differences from the reference, driven by TPU topology: NCCL wants one
process per GPU; a TPU host drives ALL its local chips from one process via
``jax.distributed.initialize``, so the runner launches ONE worker per host
(slots in the hostfile = chips, used for bookkeeping/filters, not process
counts). The rendezvous coordinator is the first included host.

Hostfile syntax is the reference's: ``hostname slots=N`` lines, ``#``
comments. Inclusion/exclusion filters use the reference's
``node1@node2:0,2`` syntax (reference runner.py:151 parse_resource_filter).
"""

import argparse
import base64
import json
import os
import shlex
import time
import subprocess
import tempfile
import sys
from collections import OrderedDict
from typing import Dict, List, Optional

from deepspeed_tpu.utils.logging import logger

DEFAULT_MASTER_PORT = 29500


def parse_args(args=None):
    parser = argparse.ArgumentParser(
        description="deepspeed-tpu multi-host launcher")
    parser.add_argument("-H", "--hostfile", type=str, default="/job/hostfile",
                        help="Hostfile: lines of '<host> slots=<n>'")
    parser.add_argument("-i", "--include", type=str, default="",
                        help="Node/slot inclusion filter, e.g. "
                             "'node1@node2:0,2'")
    parser.add_argument("-e", "--exclude", type=str, default="",
                        help="Node/slot exclusion filter")
    parser.add_argument("--num_nodes", type=int, default=-1,
                        help="Limit to first N nodes of the hostfile")
    parser.add_argument("--master_port", type=int,
                        default=DEFAULT_MASTER_PORT)
    parser.add_argument("--master_addr", type=str, default="")
    parser.add_argument("--launcher", type=str, default="ssh",
                        choices=["ssh", "pdsh", "local", "openmpi", "mpich",
                                 "mvapich"],
                        help="Multi-node backend")
    parser.add_argument("--force_multi", action="store_true",
                        help="Treat as multi-node even for one host")
    parser.add_argument("--auto_resume", action="store_true",
                        help="Supervise the job: restart it after a worker "
                             "death (preemption, crash) up to "
                             "--max_restarts times; restarted workers see "
                             "DSTPU_RESUME_ATTEMPT and resume from the "
                             "newest complete resilience checkpoint")
    parser.add_argument("--max_restarts", type=int, default=3,
                        help="Restart budget for --auto_resume")
    parser.add_argument("--max_backoff", type=float, default=60.0,
                        help="Cap (seconds) on the exponential restart "
                             "delay; watchdog exits (guardrails step "
                             "deadline, distinct rc) restart immediately")
    parser.add_argument("--watchdog_rc", type=int, default=None,
                        help="Exit code treated as a guardrails-watchdog "
                             "kill (immediate no-backoff restart). Set "
                             "this when the ds-config overrides "
                             "guardrails.watchdog.exit_code; default 113")
    parser.add_argument("--oom_rc", type=int, default=None,
                        help="Exit code treated as a memory-observatory "
                             "OOM (cause=oom, NO restart — a "
                             "deterministic OOM is a config bug). Set "
                             "this when the ds-config overrides "
                             "telemetry.memory.oom_exit_code; default 114")
    parser.add_argument("--warned_rc", type=int, default=None,
                        help="Exit code treated as a handled preemption "
                             "advance warning (live elasticity drained "
                             "but no capacity survived; cause="
                             "preemption_warned, restarted normally). Set "
                             "this when the ds-config overrides "
                             "elasticity.live.exit_code; default 115")
    parser.add_argument("--autotune", action="store_true",
                        help="Run the startup config search before "
                             "training (autotuning/; docs/PERFORMANCE.md "
                             "'Autotuning'): exports DSTPU_AUTOTUNE=1 so "
                             "every worker's config parse enables the "
                             "autotuning block. The script must supply "
                             "the batch source — initialize("
                             "autotune_batches=fn) or an explicit "
                             "deepspeed_tpu.autotune(engine, fn) call")
    parser.add_argument("--run_dir", type=str, default=None,
                        help="Goodput run dir (the job's telemetry.dir): "
                             "with --auto_resume, each attempt's run "
                             "manifest there gets its exit rc / restart "
                             "cause stamped so tools/goodput_report.py "
                             "can attribute inter-attempt downtime")
    parser.add_argument("user_script", type=str,
                        help="User training script")
    parser.add_argument("user_args", nargs=argparse.REMAINDER)
    return parser.parse_args(args)


def fetch_hostfile(path: str) -> "OrderedDict[str, int]":
    """Parse '<host> slots=<n>' lines (reference runner.py:120)."""
    resources: "OrderedDict[str, int]" = OrderedDict()
    if not os.path.isfile(path):
        return resources
    with open(path) as f:
        for line in f:
            line = line.split("#")[0].strip()
            if not line:
                continue
            try:
                hostname, slots = line.split()
                key, slot_count = slots.split("=")
                if key != "slots":
                    raise ValueError
                slot_count = int(slot_count)
            except ValueError:
                raise ValueError(f"hostfile line malformed: '{line}' "
                                 "(expected '<host> slots=<n>')")
            if hostname in resources:
                raise ValueError(f"hostfile duplicates host '{hostname}'")
            resources[hostname] = slot_count
    return resources


def _parse_filter(spec: str) -> Dict[str, Optional[List[int]]]:
    """'node1@node2:0,2' -> {node1: None, node2: [0, 2]}."""
    out: Dict[str, Optional[List[int]]] = {}
    if not spec:
        return out
    for part in spec.split("@"):
        if ":" in part:
            host, slots = part.split(":")
            out[host] = sorted(int(s) for s in slots.split(","))
        else:
            out[part] = None
    return out


def parse_inclusion_exclusion(resources: "OrderedDict[str, int]",
                              include: str,
                              exclude: str) -> "OrderedDict[str, List[int]]":
    """Apply include/exclude filters to {host: slot_count}
    (reference runner.py:151,:243). Returns {host: [slot ids]}."""
    active: "OrderedDict[str, List[int]]" = OrderedDict(
        (h, list(range(n))) for h, n in resources.items())
    inc = _parse_filter(include)
    exc = _parse_filter(exclude)
    if inc and exc:
        raise ValueError("specify only one of include/exclude filters")
    if inc:
        filtered: "OrderedDict[str, List[int]]" = OrderedDict()
        for host, slots in inc.items():
            if host not in active:
                raise ValueError(f"included host '{host}' not in hostfile")
            sel = slots if slots is not None else active[host]
            bad = set(sel) - set(active[host])
            if bad:
                raise ValueError(f"included slots {sorted(bad)} not on {host}")
            filtered[host] = sel
        return filtered
    for host, slots in exc.items():
        if host not in active:
            raise ValueError(f"excluded host '{host}' not in hostfile")
        if slots is None:
            del active[host]
        else:
            active[host] = [s for s in active[host] if s not in slots]
            if not active[host]:
                del active[host]
    return active


def encode_world_info(active: "OrderedDict[str, List[int]]") -> str:
    return base64.urlsafe_b64encode(
        json.dumps(active).encode()).decode()


def decode_world_info(blob: str) -> Dict[str, List[int]]:
    return json.loads(base64.urlsafe_b64decode(blob.encode()).decode())


def build_host_command(host_idx: int, world: "OrderedDict[str, List[int]]",
                       args, env_exports: Dict[str, str]) -> List[str]:
    """The per-host command: python -m deepspeed_tpu.launcher.launch ..."""
    world_blob = encode_world_info(world)
    hosts = list(world.keys())
    master = args.master_addr or hosts[0]
    cmd = [sys.executable, "-m", "deepspeed_tpu.launcher.launch",
           f"--world_info={world_blob}",
           f"--node_rank={host_idx}",
           f"--master_addr={master}",
           f"--master_port={args.master_port}",
           args.user_script] + list(args.user_args)
    return cmd


def build_mpi_command(active: "OrderedDict[str, List[int]]", args,
                      env_exports: Dict[str, str]) -> List[str]:
    """One ``mpirun`` launching launch.py on every host — the reference's
    OpenMPIRunner/MVAPICHRunner (launcher/multinode_runner.py:98,141). Each
    rank reads its node_rank from the MPI environment
    (OMPI_COMM_WORLD_RANK / PMI_RANK, see launch.py)."""
    hosts = list(active.keys())
    world_blob = encode_world_info(active)
    master = args.master_addr or hosts[0]
    per_rank = [sys.executable, "-m", "deepspeed_tpu.launcher.launch",
                f"--world_info={world_blob}",
                "--node_rank=-1",  # from MPI env
                f"--master_addr={master}",
                f"--master_port={args.master_port}",
                args.user_script] + list(args.user_args)
    if args.launcher == "openmpi":
        cmd = ["mpirun", "-np", str(len(hosts)),
               "--host", ",".join(f"{h}:1" for h in hosts),
               "--map-by", "ppr:1:node"]
        for k, v in env_exports.items():
            cmd += ["-x", f"{k}={v}"]
    elif args.launcher == "mvapich":
        # Reference MVAPICHRunner (multinode_runner.py:141): a hydra-style
        # mpirun with a hostfile and the MV2_* environment; the CUDA knobs
        # (MV2_USE_CUDA/SUPPORT_DL) have no TPU role and are dropped.
        fd, hostfile = tempfile.mkstemp(prefix="dstpu_mvapich_hosts_")
        with os.fdopen(fd, "w") as f:
            f.write("\n".join(hosts) + "\n")
        env_exports = dict(env_exports)
        env_exports.setdefault("MV2_SMP_USE_CMA", "0")
        env_exports.setdefault("MV2_DEBUG_SHOW_BACKTRACE", "1")
        cmd = ["mpirun", "-np", str(len(hosts)),
               "-hostfile", hostfile, "-ppn", "1"]
        for k, v in env_exports.items():
            cmd += ["-env", k, v]
    else:  # mpich
        cmd = ["mpirun", "-np", str(len(hosts)),
               "-hosts", ",".join(hosts), "-ppn", "1"]
        for k, v in env_exports.items():
            cmd += ["-genv", k, v]
    return cmd + per_rank


def propagated_env() -> Dict[str, str]:
    """Environment forwarded to workers (reference forwards NCCL*/PYTHON*
    /etc; here: JAX/XLA/TPU/PYTHON plus .deepspeed_env extras,
    reference runner.py:330-346)."""
    prefixes = ("JAX", "XLA", "TPU", "LIBTPU", "PYTHON", "DSTPU")
    env = {k: v for k, v in os.environ.items()
           if any(k.startswith(p) for p in prefixes)}
    dot_env = os.path.join(os.path.expanduser("~"), ".deepspeed_env")
    if os.path.isfile(dot_env):
        with open(dot_env) as f:
            for line in f:
                line = line.strip()
                if line and "=" in line and not line.startswith("#"):
                    k, v = line.split("=", 1)
                    env[k] = v
    return env


def main(args=None):
    args = parse_args(args)
    # ONE resolution of the effective OOM rc (telemetry/memory.py's
    # distinct exit code) — the supervisor branch, the manifest cause
    # classification and the auto-resume loop below must all agree on
    # which rc means "deterministic OOM, do not restart".
    from deepspeed_tpu.config.constants import MEMORY_OOM_EXIT_CODE_DEFAULT
    oom_rc = (args.oom_rc if args.oom_rc is not None
              else MEMORY_OOM_EXIT_CODE_DEFAULT)
    resources = fetch_hostfile(args.hostfile)
    if not resources:
        # single-node fallback: localhost with all local chips
        resources = OrderedDict([("localhost", -1)])
    if args.num_nodes > 0:
        resources = OrderedDict(list(resources.items())[:args.num_nodes])
    active = parse_inclusion_exclusion(
        OrderedDict((h, (n if n > 0 else 8)) for h, n in resources.items()),
        args.include, args.exclude)
    if not active:
        raise RuntimeError("no hosts left after filters")
    hosts = list(active.keys())
    if args.autotune:
        # DSTPU_* is in the propagated-env prefix list, so every worker
        # (local, ssh/pdsh remote, or supervisor restart) inherits it and
        # AutotuningConfig.from_dict flips enabled at config parse.
        from deepspeed_tpu.config.constants import AUTOTUNING_ENV
        os.environ[AUTOTUNING_ENV] = "1"
    env = propagated_env()

    multi_node = args.force_multi or len(hosts) > 1
    if not multi_node:
        cmd = build_host_command(0, active, args, env)
        logger.info("single-node launch: %s", " ".join(map(shlex.quote, cmd)))
        if args.auto_resume:
            # The launcher-level recovery loop (resilience/supervisor.py):
            # restart on death; the resumed incarnation reads the newest
            # complete manifest via engine.auto_resume().
            from deepspeed_tpu.resilience import Supervisor
            immediate = ({args.watchdog_rc} if args.watchdog_rc is not None
                         else None)   # None -> supervisor default (113)
            warned = ({args.warned_rc} if args.warned_rc is not None
                      else None)      # None -> supervisor default (115)
            sys.exit(Supervisor(cmd, max_restarts=args.max_restarts,
                                max_backoff=args.max_backoff,
                                immediate_restart_rcs=immediate,
                                oom_rcs={oom_rc},
                                warned_rcs=warned,
                                run_dir=args.run_dir,
                                env=env).run())
        result = subprocess.run(cmd, env={**os.environ, **env})
        sys.exit(result.returncode)

    def launch_once(attempt_env: Dict[str, str]) -> int:
        env_a = {**env, **attempt_env}
        if args.launcher in ("openmpi", "mpich", "mvapich"):
            cmd = build_mpi_command(active, args, env_a)
            logger.info("mpi launch: %s", " ".join(map(shlex.quote, cmd)))
            return subprocess.run(cmd, env={**os.environ, **env_a}).returncode

        # multi-node: one remote command per host over ssh/pdsh
        procs = []
        exports = " ".join(f"{k}={shlex.quote(v)}"
                           for k, v in env_a.items())
        for idx, host in enumerate(hosts):
            cmd = build_host_command(idx, active, args, env_a)
            remote = f"cd {shlex.quote(os.getcwd())} && {exports} " + \
                " ".join(map(shlex.quote, cmd))
            if args.launcher == "pdsh":
                full = ["pdsh", "-w", host, remote]
            else:
                full = ["ssh", host, remote]
            logger.info("launching on %s: %s", host, remote)
            procs.append(subprocess.Popen(full))

        def remote_kill():
            # Killing the local ssh/pdsh client does not reliably reach the
            # remote workers (no tty) — issue an explicit best-effort remote
            # pkill, the reference runner's abort path.
            pat = shlex.quote(
                f"deepspeed_tpu.launcher.launch.*{args.user_script}")
            for host in hosts:
                try:
                    subprocess.run(["ssh", host, f"pkill -f {pat}"],
                                   timeout=10, capture_output=True)
                except Exception:
                    pass

        return babysit(procs, on_failure=remote_kill)

    from deepspeed_tpu.telemetry.goodput import (ATTEMPT_START_WALL_ENV,
                                                 classify_exit,
                                                 finalize_attempt_manifests)

    def finalize_attempt(attempt: int, rc_: int, start_wall: float) -> None:
        """Stamp the attempt's goodput run manifests with its fate
        (best-effort — accounting must never break the recovery loop)."""
        if not args.run_dir:
            return
        from deepspeed_tpu.config.constants import (
            ELASTIC_PREEMPT_EXIT_CODE_DEFAULT,
            GUARDRAILS_WATCHDOG_EXIT_CODE_DEFAULT)
        watchdog = (args.watchdog_rc,) if args.watchdog_rc is not None \
            else (GUARDRAILS_WATCHDOG_EXIT_CODE_DEFAULT,)
        warned = (args.warned_rc,) if args.warned_rc is not None \
            else (ELASTIC_PREEMPT_EXIT_CODE_DEFAULT,)
        try:
            finalize_attempt_manifests(args.run_dir, attempt, rc_,
                                       classify_exit(rc_, watchdog,
                                                     (oom_rc,), warned),
                                       start_wall, time.time())
        except Exception as e:  # noqa: BLE001
            logger.warning("goodput manifest finalize failed: %s", e)

    t_start = time.time()
    rc = launch_once({ATTEMPT_START_WALL_ENV: repr(t_start)})
    finalize_attempt(0, rc, t_start)
    restarts = 0
    while (rc != 0 and rc != oom_rc and args.auto_resume
           and restarts < args.max_restarts):
        restarts += 1
        from deepspeed_tpu.config.constants import \
            GUARDRAILS_WATCHDOG_EXIT_CODE_DEFAULT
        from deepspeed_tpu.guardrails.retry import backoff_delay
        from deepspeed_tpu.resilience import RESUME_ATTEMPT_ENV
        watchdog_rc = (args.watchdog_rc if args.watchdog_rc is not None
                       else GUARDRAILS_WATCHDOG_EXIT_CODE_DEFAULT)
        if rc == watchdog_rc:
            delay = 0.0   # watchdog kill: the hang already burned its budget
        else:
            delay = backoff_delay(restarts - 1, base=1.0,
                                  max_delay=args.max_backoff, jitter=0.25)
        logger.warning("job died rc=%s — auto-resume restart %d/%d in %.1fs",
                       rc, restarts, args.max_restarts, delay)
        if delay:
            time.sleep(delay)
        t_start = time.time()
        rc = launch_once({RESUME_ATTEMPT_ENV: str(restarts),
                          ATTEMPT_START_WALL_ENV: repr(t_start)})
        finalize_attempt(restarts, rc, t_start)
    if rc == oom_rc and args.auto_resume:
        logger.error(
            "job died rc=%s (cause=oom) — NOT restarting: a deterministic "
            "OOM re-fires every attempt; inspect the memory crashdump "
            "(oom_step*/) and the memory_plan.json what-if table "
            "(tools/memory_report.py) for a fitting config", rc)
    sys.exit(rc)


def babysit(procs, poll_interval: float = 0.5, term_timeout: float = 10.0,
            on_failure=None) -> int:
    """Wait on all workers; first failure terminates the rest (reference
    launch.py sigkill_handler semantics — a dead rank would hang every
    collective the survivors enter). SIGTERM escalates to SIGKILL after
    ``term_timeout``; ``on_failure`` (e.g. a remote pkill) runs once on the
    first nonzero exit."""
    rc = 0
    alive = list(procs)
    while alive and rc == 0:
        finished = [p for p in alive if p.poll() is not None]
        for p in finished:
            alive.remove(p)
            if p.returncode != 0:
                rc = p.returncode
                logger.error("worker exited rc=%s — terminating the job", rc)
                if on_failure is not None:
                    on_failure()
                for q in alive:
                    q.terminate()
                break
        if not finished:
            time.sleep(poll_interval)
    for p in alive:
        try:
            p.wait(timeout=term_timeout)
        except subprocess.TimeoutExpired:
            logger.error("worker ignored SIGTERM — killing")
            p.kill()
            p.wait()
        rc = rc or p.returncode
    return rc



def ds_ssh_main(argv=None):
    """``ds-ssh-tpu`` — run a command on every hostfile host (the
    reference's ``bin/ds_ssh`` pdsh one-liner). Hosts run concurrently;
    each host's output prints with a ``[host]`` prefix once that host
    finishes; exits non-zero if any host fails."""
    parser = argparse.ArgumentParser(
        description="Run a command on all hosts of a hostfile")
    parser.add_argument("-H", "--hostfile", default="/job/hostfile")
    parser.add_argument("command", nargs=argparse.REMAINDER,
                        help="command to run on every host")
    args = parser.parse_args(argv)
    if not args.command:
        parser.error("no command given")
    resources = fetch_hostfile(args.hostfile)
    if not resources:
        parser.error(f"hostfile {args.hostfile} missing or empty")
    cmd = " ".join(shlex.quote(c) for c in args.command)
    procs = []
    for host in resources:
        if host in ("localhost", "127.0.0.1"):
            p = subprocess.Popen(["/bin/sh", "-c", cmd],
                                 stdin=subprocess.DEVNULL,
                                 stdout=subprocess.PIPE,
                                 stderr=subprocess.STDOUT, text=True)
        else:
            p = subprocess.Popen(
                ["ssh", "-n", "-o", "StrictHostKeyChecking=accept-new",
                 host, cmd],
                stdin=subprocess.DEVNULL,
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        procs.append((host, p))
    rc = 0
    for host, p in procs:
        out, _ = p.communicate()
        for line in (out or "").splitlines():
            print(f"[{host}] {line}")
        rc = rc or p.returncode
    return rc

if __name__ == "__main__":
    main()
