"""Pipeline parallelism: schedules, module contract, jitted pipeline engine."""

from deepspeed_tpu.parallel.pipe.engine import PipelineEngine
from deepspeed_tpu.parallel.pipe.module import (LayerSpec, PipeModel,
                                                TiedLayerSpec, gpt_pipe_model)
from deepspeed_tpu.parallel.pipe.pipeline import (pipeline_apply,
                                                  pipeline_spec, stack_blocks)
from deepspeed_tpu.parallel.pipe import schedule

__all__ = ["PipelineEngine", "PipeModel", "LayerSpec", "TiedLayerSpec",
           "gpt_pipe_model", "pipeline_apply", "pipeline_spec",
           "stack_blocks", "schedule"]
