"""Pipelined execution over the ``pipe`` mesh axis — TPU-native.

The reference drives pipeline parallelism from the host: a Python scheduler
(`pipe/schedule.py`) dispatches per-tick instructions whose Send/Recv are
NCCL broadcasts between adjacent ranks (`pipe/engine.py:1209`,
`pipe/p2p.py:31`). On TPU that design would serialise dispatch; instead the
WHOLE pipelined step is one jitted program: a ``shard_map`` manual over the
``pipe`` axis ONLY (`axis_names={'pipe'}`) runs every stage in SPMD, a
``lax.scan`` over schedule ticks moves microbatch activations between
neighbouring stages with ``lax.ppermute`` over ICI, and reverse-mode AD of
that scan yields the backward pipeline automatically (ppermute transposes
to the reverse shift) — the moral equivalent of the 1F1B instruction tape,
scheduled by XLA. Because ``data``/``model``/``sequence`` stay AUTO axes,
ZeRO data-sharding and Megatron tensor parallelism inside each block keep
working through GSPMD — the pp × tp × dp composition of the reference's 3D
topology (pipe/topology.py:246) without hand-built process groups.

Model layout contract (the ``PipelineModule`` analogue, pipe/module.py:87):
embedding and loss head live OUTSIDE the pipelined segment (computed under
plain GSPMD, which also ties input/output embeddings for free — the
reference needs TiedLayerSpec + a dedicated allreduce group for this,
module.py:73); the pipelined body is a stack of L structurally identical
blocks, stacked on a leading dim that is sharded over ``pipe`` so each
stage owns L/S consecutive blocks. Per-microbatch side inputs (attention
masks) travel as ``aux``, indexed by the schedule so stage s at tick t sees
the aux of the microbatch it is actually processing (m = t − s).
"""

import functools
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from deepspeed_tpu.utils.jax_compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from deepspeed_tpu.parallel.mesh import PIPE_AXIS


def stack_blocks(block_params_list):
    """Stack per-block param pytrees into one pytree with leading dim L."""
    return jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs, axis=0), *block_params_list)


def pipeline_spec(blocks_params) -> Any:
    """PartitionSpec tree sharding the stacked block dim over ``pipe``."""
    return jax.tree_util.tree_map(
        lambda x: P(PIPE_AXIS, *([None] * (x.ndim - 1))), blocks_params)


def default_skip_bubble() -> bool:
    """Whether fill/drain ticks skip their compute (``lax.cond`` on the
    per-rank validity predicate — the reference's 1F1B executes no bubble
    instructions by construction, pipe/schedule.py:182; here the cond
    saves the (S−1)/(M+S−1) bubble energy). Resolved at trace time:
    ``DSTPU_SKIP_BUBBLE`` = ``1``/``0`` forces it; default = TPU only.
    On XLA:CPU the cond composes with ZeRO-1's data-axis apply
    collectives into a deterministic second-step rendezvous DEADLOCK
    (pinned round 5 — ``tools/repro_cond_ppermute_deadlock.py``; ZeRO-0
    + cond runs fine and is CI-exercised, docs/ISSUES.md #1)."""
    import os

    v = os.environ.get("DSTPU_SKIP_BUBBLE", "")
    if v in ("0", "1"):
        return v == "1"
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:  # pragma: no cover — no backend
        return False


# Cache of jitted pipelined programs: rebuilding shard_map+jit per call would
# recompile on every eager invocation. Keyed by everything that changes the
# traced program except array shapes (jit handles shape retracing itself).
_PIPELINE_CACHE = {}


def pipeline_apply_manual(block_fn: Callable,
                          stage_blocks: Any,
                          x_all: jax.Array,
                          aux_all: Any,
                          keys: Optional[jax.Array],
                          *,
                          stages: int,
                          num_microbatches: int,
                          remat_blocks: bool = True,
                          broadcast_output: bool = True,
                          pass_layer_idx: bool = False,
                          block_aux: bool = False,
                          skip_bubble: Optional[bool] = None,
                          rank: Optional[jax.Array] = None):
    """The manual-region pipeline body: call INSIDE a shard_map already
    manual over ``pipe`` (``stage_blocks`` leaves carry the local
    ``[L/S, ...]`` shard; ``x_all`` ``[M, mb, ...]`` is pipe-replicated).

    With ``broadcast_output`` (default) the last stage's microbatch outputs
    are psum-broadcast to every pipe rank in fp32; with it off the raw
    last-stage slice is returned and ONLY rank ``stages-1`` holds valid
    data — callers that mask per-rank themselves (the 1-bit pipeline
    engine) use this to keep gradient provenance per stage.

    With ``stages == 1`` this degenerates to a scan over blocks per
    microbatch (no collectives emitted).

    ``pass_layer_idx``: call ``block_fn(p, h, a, k, global_layer_idx)``
    — the GLOBAL block index (stage offset + local scan index), which
    per-layer schedules like Progressive Layer Drop need (the flat
    families read it from the Python loop counter; the reference threads
    PLD kwargs through engine.forward into each layer,
    /root/reference/deepspeed/runtime/engine.py:1085).

    ``block_aux``: block_fn returns ``(h, aux_scalar)`` (e.g. a MoE
    load-balance loss). The return value grows a second element: the
    fp32 aux total summed over every (microbatch, layer) — bubble ticks
    masked out, psum'd over ``pipe`` — which the caller folds into the
    loss (divide by M for the per-microbatch mean). Reference analogue:
    DeepSpeed-MoE's aux losses ride the module outputs through the
    pipeline the same way."""
    M = num_microbatches
    if skip_bubble is None:
        skip_bubble = default_skip_bubble()
    fn = jax.checkpoint(block_fn) if remat_blocks else block_fn
    n_local = jax.tree_util.tree_leaves(stage_blocks)[0].shape[0]

    def stage_apply(h, a, key, base):
        # Apply this stage's L/S blocks in order (scan keeps the program
        # small; blocks are structurally identical by contract).
        def body(carry, xs):
            h, aux = carry
            p, i = xs
            k = None if key is None else jax.random.fold_in(key, i)
            args = (p, h, a, k) + ((base + i,) if pass_layer_idx else ())
            y = fn(*args)
            if block_aux:
                y, a_l = y
                aux = aux + a_l.astype(jnp.float32)
            return (y, aux), None

        (h, aux), _ = jax.lax.scan(body, (h, jnp.float32(0.0)),
                                   (stage_blocks, jnp.arange(n_local)))
        return h, aux

    def aux_at(idx):
        if aux_all is None:
            return None
        return jax.tree_util.tree_map(
            lambda a: jax.lax.dynamic_index_in_dim(a, idx, axis=0,
                                                   keepdims=False), aux_all)

    if stages == 1:
        def per_mb(mb, i):
            key = None if keys is None else jax.random.fold_in(keys, i)
            return stage_apply(mb, aux_at(i), key, 0)

        if aux_all is None:
            out, auxs = jax.vmap(per_mb)(x_all, jnp.arange(M))
        else:
            # aux indexing is data-dependent per microbatch — use scan
            def body(_, mi):
                mb, i = mi
                return None, per_mb(mb, i)

            _, (out, auxs) = jax.lax.scan(body, None, (x_all, jnp.arange(M)))
        return (out, jnp.sum(auxs)) if block_aux else out

    T = M + stages - 1
    if rank is None:
        # Fine under a fully-manual caller; the partial-manual
        # pipeline_apply path passes a sharded-iota rank instead because
        # old jax lowers axis_index there to a PartitionId HLO the SPMD
        # partitioner rejects (utils/jax_compat.py).
        rank = jax.lax.axis_index(PIPE_AXIS)
    shift = [(i, (i + 1) % stages) for i in range(stages)]

    def tick(carry, t):
        buf, aux_acc = carry
        inject = jax.lax.dynamic_index_in_dim(
            x_all, jnp.clip(t, 0, M - 1), axis=0, keepdims=False)
        h = jnp.where(rank == 0, inject, buf)
        # Stage `rank` processes microbatch m = t - rank at tick t;
        # fill/drain ticks (m outside [0, M)) carry garbage that no
        # valid tick ever consumes (producer (r-1, t-1) has the same m
        # as consumer (r, t)). Wall-clock is the critical-path bound
        # T·stage_time either way (the ppermute keeps ranks in lockstep;
        # tests/test_pipeline.py::test_step_time_approaches_bubble_
        # bound), so skip_bubble saves the (S-1)/(M+S-1) bubble ENERGY:
        # default on for TPU, off for XLA:CPU where the cond composes
        # with ZeRO-1 apply collectives into a second-step rendezvous
        # deadlock (pinned: tools/repro_cond_ppermute_deadlock.py,
        # docs/ISSUES.md #1; the ZeRO-0 cond path is CI-exercised by
        # TestBubbleSkip).
        m = t - rank
        a = aux_at(jnp.clip(m, 0, M - 1))
        k = (None if keys is None
             else jax.random.fold_in(jax.random.fold_in(keys, t), rank))
        valid = jnp.logical_and(m >= 0, m < M)
        if skip_bubble:
            # Fill/drain ticks carry garbage no valid tick consumes —
            # skip their compute entirely (the reference's 1F1B executes
            # no bubble instructions by construction, pipe/schedule.py).
            # Per-rank divergence is fine under the manual shard_map: the
            # ppermute below still runs on every rank in lockstep.
            y, aux_y = jax.lax.cond(
                valid,
                lambda: stage_apply(h, a, k, rank * n_local),
                lambda: (h, jnp.float32(0.0)))
        else:
            y, aux_y = stage_apply(h, a, k, rank * n_local)
        # Bubble ticks' aux contribution must not pollute the loss.
        aux_acc = aux_acc + jnp.where(valid, aux_y, 0.0)
        buf = jax.lax.ppermute(y, PIPE_AXIS, shift)
        return (buf, aux_acc), y

    (_, aux_total), ys = jax.lax.scan(
        tick, (jnp.zeros_like(x_all[0]), jnp.float32(0.0)), jnp.arange(T))
    # Last stage produced microbatch m at tick m + S - 1.
    out = jax.lax.dynamic_slice_in_dim(ys, stages - 1, M, axis=0)
    if block_aux:
        # Each rank accumulated its own blocks' aux; the psum yields the
        # total over every (microbatch, layer), identical on all ranks.
        aux_total = jax.lax.psum(aux_total, PIPE_AXIS)
    if not broadcast_output:
        return (out, aux_total) if block_aux else out
    # Hand the result to every pipe rank (the reference broadcasts the
    # final-stage loss similarly, pipe/engine.py:453); activations of
    # non-final stages are discarded by the where. The psum runs in fp32:
    # a bf16 all-reduce under a partial-manual shard_map crashes the XLA
    # CPU backend ("Invalid binary instruction opcode copy"), and fp32
    # summation is the numerically safer choice anyway.
    masked = jnp.where(rank == stages - 1, out,
                       jnp.zeros_like(out)).astype(jnp.float32)
    out = jax.lax.psum(masked, PIPE_AXIS).astype(out.dtype)
    return (out, aux_total) if block_aux else out


def pipeline_apply(block_fn: Callable,
                   blocks_params: Any,
                   x: jax.Array,
                   mesh: Mesh,
                   *,
                   aux: Any = None,
                   rng: Optional[jax.Array] = None,
                   num_microbatches: Optional[int] = None,
                   remat_blocks: bool = True,
                   pass_layer_idx: bool = False,
                   block_aux: bool = False,
                   skip_bubble: Optional[bool] = None):
    """Run the stacked-block pipeline over microbatches.

    block_fn(params_one_block, x, aux_or_None, rng_or_None) -> x
    blocks_params: pytree, leaves [L, ...] — L % pipe_size == 0
    x: [M, mb, ...] microbatched activations (M = num_microbatches)
    aux: optional pytree of per-microbatch side inputs, leaves [M, ...]
         (e.g. attention masks) — handed to every block of the stage
         processing that microbatch
    rng: PRNG key for per-block dropout (None ≡ deterministic)

    Returns [M, mb, ...] last-stage outputs. With pipe_size == 1 this
    degenerates to a scan over blocks (no collectives emitted). Only the
    ``pipe`` axis is manual in the shard_map — tensor-parallel specs on the
    block params and data sharding on the batch keep working via GSPMD.
    """
    stages = mesh.shape.get(PIPE_AXIS, 1)
    L = jax.tree_util.tree_leaves(blocks_params)[0].shape[0]
    if L % stages:
        raise ValueError(f"{L} blocks not divisible by {stages} pipeline stages")
    M = num_microbatches if num_microbatches is not None else x.shape[0]
    if x.shape[0] != M:
        raise ValueError(f"x has {x.shape[0]} microbatches, expected {M}")

    if skip_bubble is None:
        skip_bubble = default_skip_bubble()
    if stages == 1:
        return pipeline_apply_manual(block_fn, blocks_params, x, aux, rng,
                                     stages=1, num_microbatches=M,
                                     remat_blocks=remat_blocks,
                                     pass_layer_idx=pass_layer_idx,
                                     block_aux=block_aux,
                                     skip_bubble=skip_bubble)

    from deepspeed_tpu.utils.jax_compat import NATIVE_SHARD_MAP
    if not NATIVE_SHARD_MAP:
        # Old jax: the partial-manual pipeline program crashes (C-level
        # abort) this XLA CPU backend during compilation. Fail as a
        # catchable error instead of killing the host process.
        raise NotImplementedError(
            "pipeline parallelism (stages > 1) requires a jax with native "
            "shard_map; this jax's XLA backend aborts compiling the "
            "partial-manual pipeline program")

    compute_dtype = x.dtype

    def pipelined(stage_blocks, x_all, aux_all, keys, rank_arr):
        # stage_blocks leaves: [L/S, ...] (pipe dim stripped; other axes
        # remain GSPMD-auto); x_all: [M, mb, ...] replicated across pipe.
        # x crosses the shard_map boundary in fp32 (see psum note in
        # pipeline_apply_manual: the cotangent of a pipe-replicated input
        # is a psum, which must not run in bf16 under a partial-manual
        # shard_map). rank_arr is a pipe-sharded iota, so its single local
        # element IS this shard's stage index — the axis_index equivalent
        # that survives old-jax partial-manual lowering.
        return pipeline_apply_manual(
            block_fn, stage_blocks, x_all.astype(compute_dtype), aux_all,
            keys, stages=stages, num_microbatches=M,
            remat_blocks=remat_blocks, broadcast_output=True,
            pass_layer_idx=pass_layer_idx, block_aux=block_aux,
            skip_bubble=skip_bubble, rank=rank_arr[0])

    blocks_treedef = jax.tree_util.tree_structure(blocks_params)
    blocks_ndims = tuple(l.ndim for l in jax.tree_util.tree_leaves(blocks_params))
    aux_treedef = (None if aux is None
                   else jax.tree_util.tree_structure(aux))
    key = (block_fn, mesh, stages, M, remat_blocks, rng is None,
           blocks_treedef, blocks_ndims, aux_treedef, compute_dtype,
           pass_layer_idx, block_aux, skip_bubble)
    if key not in _PIPELINE_CACHE:
        def entry(blocks_arg, x_arg, aux_arg, rng_arg):
            return shard_map(
                pipelined,
                mesh=mesh,
                in_specs=(pipeline_spec(blocks_arg), P(), P(), P(),
                          P(PIPE_AXIS)),
                out_specs=(P(), P()) if block_aux else P(),
                axis_names={PIPE_AXIS},
                check_vma=False,
            )(blocks_arg, x_arg, aux_arg, rng_arg,
              jnp.arange(stages, dtype=jnp.int32))

        # Partial-manual shard_map only traces under jit; the jit also makes
        # repeated eager calls hit the compile cache.
        _PIPELINE_CACHE[key] = jax.jit(entry)
    return _PIPELINE_CACHE[key](blocks_params, x.astype(jnp.float32), aux, rng)
