"""Pipelined execution over the ``pipe`` mesh axis — TPU-native.

The reference drives pipeline parallelism from the host: a Python scheduler
(`pipe/schedule.py`) dispatches per-tick instructions whose Send/Recv are
NCCL broadcasts between adjacent ranks (`pipe/engine.py:1209`,
`pipe/p2p.py:31`). On TPU that design would serialise dispatch; instead the
WHOLE pipelined step is one jitted program: a ``shard_map`` manual over the
``pipe`` axis ONLY (`axis_names={'pipe'}`) runs every stage in SPMD, a
``lax.scan`` over schedule ticks moves microbatch activations between
neighbouring stages with ``lax.ppermute`` over ICI, and reverse-mode AD of
that scan yields the backward pipeline automatically (ppermute transposes
to the reverse shift) — the moral equivalent of the 1F1B instruction tape,
scheduled by XLA. Because ``data``/``model``/``sequence`` stay AUTO axes,
ZeRO data-sharding and Megatron tensor parallelism inside each block keep
working through GSPMD — the pp × tp × dp composition of the reference's 3D
topology (pipe/topology.py:246) without hand-built process groups.

Model layout contract (the ``PipelineModule`` analogue, pipe/module.py:87):
embedding and loss head live OUTSIDE the pipelined segment (computed under
plain GSPMD, which also ties input/output embeddings for free — the
reference needs TiedLayerSpec + a dedicated allreduce group for this,
module.py:73); the pipelined body is a stack of L structurally identical
blocks, stacked on a leading dim that is sharded over ``pipe`` so each
stage owns L/S consecutive blocks.
"""

import functools
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from deepspeed_tpu.parallel.mesh import DATA_AXIS, PIPE_AXIS


def stack_blocks(block_params_list):
    """Stack per-block param pytrees into one pytree with leading dim L."""
    return jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs, axis=0), *block_params_list)


def pipeline_spec(blocks_params) -> Any:
    """PartitionSpec tree sharding the stacked block dim over ``pipe``."""
    return jax.tree_util.tree_map(
        lambda x: P(PIPE_AXIS, *([None] * (x.ndim - 1))), blocks_params)


def pipeline_apply(block_fn: Callable,
                   blocks_params: Any,
                   x: jax.Array,
                   mesh: Mesh,
                   *,
                   rng: Optional[jax.Array] = None,
                   num_microbatches: Optional[int] = None,
                   remat_blocks: bool = True) -> jax.Array:
    """Run the stacked-block pipeline over microbatches.

    block_fn(params_one_block, x, rng_or_None) -> x  (one transformer block)
    blocks_params: pytree, leaves [L, ...] — L % pipe_size == 0
    x: [M, mb, ...] microbatched activations (M = num_microbatches)
    rng: PRNG key for per-block dropout (None ≡ deterministic)

    Returns [M, mb, ...] last-stage outputs. With pipe_size == 1 this
    degenerates to a scan over blocks (no collectives emitted). Only the
    ``pipe`` axis is manual in the shard_map — tensor-parallel specs on the
    block params and data sharding on the batch keep working via GSPMD.
    """
    stages = mesh.shape.get(PIPE_AXIS, 1)
    L = jax.tree_util.tree_leaves(blocks_params)[0].shape[0]
    if L % stages:
        raise ValueError(f"{L} blocks not divisible by {stages} pipeline stages")
    M = num_microbatches if num_microbatches is not None else x.shape[0]
    if x.shape[0] != M:
        raise ValueError(f"x has {x.shape[0]} microbatches, expected {M}")

    fn = block_fn
    if remat_blocks:
        fn = jax.checkpoint(block_fn)

    def stage_apply(stage_blocks, h, key):
        # Apply this stage's L/S blocks in order (scan keeps the program
        # small; blocks are structurally identical by contract).
        def body(h, xs):
            p, i = xs
            k = None if key is None else jax.random.fold_in(key, i)
            return fn(p, h, k), None

        n = jax.tree_util.tree_leaves(stage_blocks)[0].shape[0]
        h, _ = jax.lax.scan(body, h, (stage_blocks, jnp.arange(n)))
        return h

    if stages == 1:
        def per_mb(mb, i):
            key = None if rng is None else jax.random.fold_in(rng, i)
            return stage_apply(blocks_params, mb, key)

        return jax.vmap(per_mb)(x, jnp.arange(M))

    T = M + stages - 1

    compute_dtype = x.dtype

    def pipelined(stage_blocks, x_all, *key):
        # stage_blocks leaves: [L/S, ...] (pipe dim stripped; other axes
        # remain GSPMD-auto); x_all: [M, mb, ...] replicated across pipe.
        # x crosses the shard_map boundary in fp32 (see psum note below:
        # the cotangent of a pipe-replicated input is a psum, which must
        # not run in bf16 under a partial-manual shard_map).
        x_all = x_all.astype(compute_dtype)
        keys = key[0] if key else None
        rank = jax.lax.axis_index(PIPE_AXIS)
        shift = [(i, (i + 1) % stages) for i in range(stages)]

        def tick(carry, t):
            buf = carry
            inject = jax.lax.dynamic_index_in_dim(
                x_all, jnp.clip(t, 0, M - 1), axis=0, keepdims=False)
            h = jnp.where(rank == 0, inject, buf)
            k = (None if keys is None
                 else jax.random.fold_in(jax.random.fold_in(keys, t), rank))
            y = stage_apply(stage_blocks, h, k)
            buf = jax.lax.ppermute(y, PIPE_AXIS, shift)
            return buf, y

        _, ys = jax.lax.scan(tick, jnp.zeros_like(x_all[0]),
                             jnp.arange(T))
        # Last stage produced microbatch m at tick m + S - 1.
        out = jax.lax.dynamic_slice_in_dim(ys, stages - 1, M, axis=0)
        # Hand the result to every pipe rank (the reference broadcasts the
        # final-stage loss similarly, pipe/engine.py:453); activations of
        # non-final stages are discarded by the where. The psum runs in fp32:
        # a bf16 all-reduce under a partial-manual shard_map crashes the XLA
        # CPU backend ("Invalid binary instruction opcode copy"), and fp32
        # summation is the numerically safer choice anyway.
        masked = jnp.where(rank == stages - 1, out,
                           jnp.zeros_like(out)).astype(jnp.float32)
        return jax.lax.psum(masked, PIPE_AXIS).astype(out.dtype)

    args = (blocks_params, x.astype(jnp.float32)) + \
        (() if rng is None else (rng,))
    in_specs = (pipeline_spec(blocks_params), P()) + \
        (() if rng is None else (P(),))
    return shard_map(
        pipelined,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=P(),
        axis_names={PIPE_AXIS},
        check_vma=False,
    )(*args)
