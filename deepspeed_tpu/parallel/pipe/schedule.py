"""Pipeline instruction schedules — pure-Python generators.

Functional port of the reference's backend-agnostic schedule layer
(``deepspeed/runtime/pipe/schedule.py``): a schedule yields, per engine
"step", the list of instructions a given stage executes. The reference's
``TrainSchedule`` (:182) interleaves forward/backward by step parity (1F1B
with alternating even/odd ticks); ``InferenceSchedule`` (:129) is
forward-only; ``DataParallelSchedule`` (:292) degenerates to pure DP.

On TPU the hot path executes the whole pipeline inside one jitted shard_map
program (``pipeline.py``) — XLA schedules the real overlap — but these
generators remain the source of truth for (a) host-driven execution and
microbatch accounting, (b) schedule unit tests (reference
tests/unit/test_pipe_schedule.py), and (c) bubble/utilisation analysis.
"""

from typing import Iterator, List


class PipeInstruction:
    """Base instruction. kwargs become attributes (reference schedule.py:317)."""

    def __init__(self, **kwargs):
        self.name = self.__class__.__name__
        self.kwargs = kwargs
        for k, v in kwargs.items():
            setattr(self, k, v)

    def __repr__(self):
        if not self.kwargs:
            return self.name
        args = ", ".join(f"{k}={v}" for k, v in self.kwargs.items())
        return f"{self.name}({args})"

    def __eq__(self, other):
        return (type(self) is type(other)) and self.kwargs == other.kwargs

    def __hash__(self):
        return hash((self.name, tuple(sorted(self.kwargs.items()))))


class OptimizerStep(PipeInstruction):
    pass


class ReduceGrads(PipeInstruction):
    pass


class ReduceTiedGrads(PipeInstruction):
    pass


class LoadMicroBatch(PipeInstruction):
    pass  # kwargs: buffer_id


class ForwardPass(PipeInstruction):
    pass  # kwargs: buffer_id


class BackwardPass(PipeInstruction):
    pass  # kwargs: buffer_id


class SendActivation(PipeInstruction):
    pass  # kwargs: buffer_id


class RecvActivation(PipeInstruction):
    pass  # kwargs: buffer_id


class SendGrad(PipeInstruction):
    pass  # kwargs: buffer_id


class RecvGrad(PipeInstruction):
    pass  # kwargs: buffer_id


class PipeSchedule:
    """Iterable of per-step instruction lists for one stage
    (reference schedule.py:12)."""

    def __init__(self, micro_batches: int, stages: int, stage_id: int):
        if not 0 <= stage_id < stages:
            raise ValueError(f"stage_id {stage_id} out of range [0,{stages})")
        self.micro_batches = micro_batches
        self.stages = stages
        self.stage_id = stage_id
        self.prev_stage = stage_id - 1
        self.next_stage = stage_id + 1

    def steps(self) -> Iterator[List[PipeInstruction]]:
        raise NotImplementedError

    def num_pipe_buffers(self) -> int:
        return self.micro_batches

    @property
    def is_first_stage(self) -> bool:
        return self.stage_id == 0

    @property
    def is_last_stage(self) -> bool:
        return self.stage_id == self.stages - 1

    def _valid_micro_batch(self, mb: int) -> bool:
        return 0 <= mb < self.micro_batches

    def _valid_stage(self, s: int) -> bool:
        return 0 <= s < self.stages

    def __iter__(self):
        return self.steps()

    def __len__(self):
        return sum(1 for _ in self.steps())


class InferenceSchedule(PipeSchedule):
    """Forward-only pipelining (reference schedule.py:129)."""

    def steps(self):
        total_steps = self.micro_batches + self.stages - 1
        for step_id in range(total_steps):
            cmds = []
            micro_batch_id = step_id - self.stage_id
            if self._valid_micro_batch(micro_batch_id):
                buf = micro_batch_id % self.num_pipe_buffers()
                if self.is_first_stage:
                    cmds.append(LoadMicroBatch(buffer_id=buf))
                else:
                    cmds.append(RecvActivation(buffer_id=buf))
                cmds.append(ForwardPass(buffer_id=buf))
                if not self.is_last_stage:
                    cmds.append(SendActivation(buffer_id=buf))
            yield cmds

    def num_pipe_buffers(self):
        return min(2, self.micro_batches)


class TrainSchedule(PipeSchedule):
    """1F1B-by-parity training schedule (semantics of reference
    schedule.py:182): 2*(M+S-1) ticks. Stage s runs the forward of
    microbatch m at tick ``2m + s`` (its parity ticks) and the backward at
    tick ``2m + 2S - s - 1`` (opposite parity), so steady-state alternates
    one-forward-one-backward and backward of m at stage s follows backward
    at stage s+1 by exactly one tick. Transfers are emitted one tick after
    the producing compute; ends with grad reduction + optimizer step."""

    def steps(self):
        S = self.stages
        s = self.stage_id
        total_steps = 2 * (self.micro_batches + S - 1)
        for t in range(total_steps):
            cmds = []

            # Ship results produced last tick.
            if self._valid_stage(self.next_stage):
                m = (t - 1 - s)
                if m % 2 == 0 and self._valid_micro_batch(m // 2):
                    cmds.append(SendActivation(
                        buffer_id=self._buffer_idx(m // 2)))
            if self._valid_stage(self.prev_stage):
                m = (t - (2 * S - s - 1) - 1)
                if m % 2 == 0 and self._valid_micro_batch(m // 2):
                    cmds.append(SendGrad(buffer_id=self._buffer_idx(m // 2)))

            # This tick's compute (+ its ingest).
            mf = (t - s)
            if mf % 2 == 0 and self._valid_micro_batch(mf // 2):
                buf = self._buffer_idx(mf // 2)
                if self.is_first_stage:
                    cmds.append(LoadMicroBatch(buffer_id=buf))
                else:
                    cmds.append(RecvActivation(buffer_id=buf))
                cmds.append(ForwardPass(buffer_id=buf))
            mb = (t - (2 * S - s - 1))
            if mb % 2 == 0 and self._valid_micro_batch(mb // 2):
                buf = self._buffer_idx(mb // 2)
                if self._valid_stage(self.next_stage):
                    cmds.append(RecvGrad(buffer_id=buf))
                cmds.append(BackwardPass(buffer_id=buf))

            if t == total_steps - 1:
                cmds.append(ReduceTiedGrads())
                cmds.append(ReduceGrads())
                cmds.append(OptimizerStep())
            yield cmds

    def num_pipe_buffers(self):
        """Max outstanding microbatches for this stage (reference :277):
        earlier stages hold more in-flight forwards. The +1 matches the
        reference sizing so a forward landing on the same tick as a SendGrad
        never shares that microbatch's buffer — safe even for an executor
        with asynchronous sends."""
        buffers = min(self.stages - self.stage_id + 1, self.micro_batches)
        return max(2, buffers)

    def _buffer_idx(self, micro_batch_id: int) -> int:
        return micro_batch_id % self.num_pipe_buffers()


class DataParallelSchedule(PipeSchedule):
    """Degenerate single-stage schedule ≡ plain DP (reference :292)."""

    def steps(self):
        for step_id in range(self.micro_batches):
            cmds = [LoadMicroBatch(buffer_id=0), ForwardPass(buffer_id=0),
                    BackwardPass(buffer_id=0)]
            if step_id == self.micro_batches - 1:
                cmds.extend([ReduceGrads(), OptimizerStep()])
            yield cmds

    def num_pipe_buffers(self):
        return 1


def bubble_fraction(micro_batches: int, stages: int) -> float:
    """Pipeline bubble overhead (S-1)/(M+S-1) — utilisation analysis."""
    return (stages - 1) / (micro_batches + stages - 1)
