"""Pipeline model description — the ``PipelineModule`` analogue.

Reference (``deepspeed/runtime/pipe/module.py``): a layer list built from
``LayerSpec``/``TiedLayerSpec`` (:25, :73), partitioned over stages by
uniform/param-count/regex policies (:355), with tied-embedding comm groups.

TPU-native contract (``PipeModel``): the pipelined segment must be a stack
of structurally identical blocks (leading dim L sharded over ``pipe``);
embedding + head are plain functions outside the pipeline, so weight tying
is ordinary parameter sharing instead of a dedicated allreduce group.
``LayerSpec`` is kept for API familiarity and for host-side stage
assignment of *heterogeneous* inference pipelines (partition_uniform /
partition_balanced, reference runtime/utils.py:342,:408 — in
deepspeed_tpu.runtime.utils).
"""

from dataclasses import dataclass
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp


class LayerSpec:
    """Delayed-build layer descriptor (reference pipe/module.py:25)."""

    def __init__(self, typename, *args, **kwargs):
        self.typename = typename
        self.args = args
        self.kwargs = kwargs

    def build(self):
        return self.typename(*self.args, **self.kwargs)

    def __repr__(self):
        return f"LayerSpec({getattr(self.typename, '__name__', self.typename)})"


class TiedLayerSpec(LayerSpec):
    """Layer sharing weights with another layer by key (reference :73).
    In the functional pipeline, tying is expressed by both layers reading
    the same param subtree — record the key so builders can wire it."""

    def __init__(self, key, typename, *args, forward_fn=None, **kwargs):
        super().__init__(typename, *args, **kwargs)
        self.key = key
        self.forward_fn = forward_fn


@dataclass
class PipeModel:
    """Functional pipeline model: loss = head(embed(batch) |> blocks).

    - embed_fn(params, batch, rng)                  -> activations [mb, ...]
    - block_fn(one_block_params, x, aux, rng)       -> activations
    - head_fn(params, activations, batch)           -> scalar loss
    - aux_fn(params, batch) -> per-microbatch side input for the blocks
      (e.g. an attention mask) or None
    - params: {"embed": ..., "blocks": stacked [L, ...], "head": ...}

    embed_fn/head_fn receive the FULL params dict, so weight tying (e.g.
    the LM head reading params["embed"]["wte"]) is plain parameter sharing.
    """

    embed_fn: Callable
    block_fn: Callable
    head_fn: Callable
    params: Any
    num_blocks: int
    aux_fn: Optional[Callable] = None
    # block_fn takes a 5th arg: the GLOBAL layer index (stage offset +
    # local position) — needed by per-layer schedules (PLD).
    block_takes_layer_idx: bool = False
    # block_fn returns (h, aux_scalar): the pipeline masks bubble ticks,
    # psums the aux over pipe, and the engine adds mean-per-microbatch
    # aux to the loss (MoE load-balance losses).
    block_returns_aux: bool = False

    def check(self, pipe_size: int) -> None:
        if self.num_blocks % pipe_size:
            raise ValueError(
                f"{self.num_blocks} blocks not divisible by pipe={pipe_size}")


def gpt_pipe_model(cfg, rng_key=None, example_batch=None,
                   params=None) -> PipeModel:
    """Build a PipeModel from the in-tree GPT family (models/gpt.py):
    embedding + dropout outside, L GPTBlocks pipelined (attention masks
    travel as aux), ln_f + LM head (tied per cfg.tie_embeddings) +
    cross-entropy outside. ``params``: an existing flat GPT param tree
    (wte/wpe/h_i/ln_f layout) to re-pack instead of fresh-initialising —
    used when a caller hands pretrained weights to the pipeline or
    param-offload tiers."""
    import flax.linen as nn

    from deepspeed_tpu.models.gpt import GPT, GPTBlock, shift_labels

    if rng_key is None:
        rng_key = jax.random.PRNGKey(0)
    if example_batch is None:
        example_batch = {"input_ids": jnp.zeros((2, 16), jnp.int32)}

    # Initialise through the reference model so shapes/naming match the
    # non-pipelined family, then re-pack into the PipeModel layout.
    if params is not None:
        flat = params
    else:
        model = GPT(cfg)
        variables = model.init({"params": rng_key, "dropout": rng_key},
                               example_batch)
        flat = variables["params"]

    moe = getattr(cfg, "moe_experts", 0) > 0
    if moe and cfg.moe_layer_freq != 1:
        raise ValueError(
            "MoE x pipeline needs structurally identical blocks "
            "(the stacked-block contract): use moe_layer_freq=1 so every "
            f"block carries the MoE FFN (got {cfg.moe_layer_freq})")
    block = GPTBlock(cfg, moe=moe)
    from deepspeed_tpu.parallel.pipe.pipeline import stack_blocks

    blocks = stack_blocks([flat[f"h_{i}"] for i in range(cfg.num_layers)])
    head = {"ln_f": flat["ln_f"]}
    if not cfg.tie_embeddings:
        head["lm_head"] = flat["lm_head"]
    params = {
        "embed": {"wte": flat["wte"], "wpe": flat["wpe"]},
        "blocks": blocks,
        "head": head,
    }

    def embed_fn(params, batch, rng):
        from deepspeed_tpu.ops.embedding import embedding_lookup

        ids = batch["input_ids"]
        s = ids.shape[1]
        emb = params["embed"]
        tok = embedding_lookup(
            emb["wte"], ids,
            matmul_grad=getattr(cfg, "embed_grad_matmul", False),
            sparse_grad_axes=getattr(cfg, "sparse_embedding_grad", None))
        x = tok.astype(cfg.dtype) + emb["wpe"][:s][None].astype(cfg.dtype)
        if rng is not None and cfg.dropout_rate > 0.0:
            keep = jax.random.bernoulli(rng, 1.0 - cfg.dropout_rate, x.shape)
            x = jnp.where(keep, x / (1.0 - cfg.dropout_rate), 0.0)
        return x

    def aux_fn(params, batch):
        am = batch.get("attention_mask")
        # [mb, S] -> broadcastable [mb, 1, 1, S] attend-mask for GPTBlock.
        mask = (None if am is None
                else am[:, None, None, :].astype(jnp.bool_))
        theta = batch.get("pld_theta")
        if theta is None:
            return mask
        # Progressive Layer Drop rides as aux so every stage sees the
        # step's theta (reference threads it through engine.forward,
        # /root/reference/deepspeed/runtime/engine.py:1085; here the
        # pipelined schedule delivers it with the microbatch).
        return {"attn_mask": mask, "pld_theta": jnp.float32(theta)}

    def _unpack_aux(aux):
        if isinstance(aux, dict):
            return aux.get("attn_mask"), aux.get("pld_theta")
        return aux, None

    def block_fn(p, x, aux, rng, layer_idx=0):
        mask, theta = _unpack_aux(aux)
        if rng is None or cfg.dropout_rate == 0.0:
            # MoE routing needs a (deterministic-OK) rng collection only
            # when dropout is active; the top-k router itself is
            # deterministic.
            y = block.apply({"params": p}, x, mask, True)
        else:
            y = block.apply({"params": p}, x, mask, False,
                            rngs={"dropout": rng})
        aux_l = None
        if moe:
            y, aux_l = y
        if theta is not None and rng is not None:
            # The SAME keep schedule as the flat families — one shared
            # implementation so the pipelined trajectory cannot drift.
            from deepspeed_tpu.runtime.progressive_layer_drop import \
                pld_keep_gate
            gate = pld_keep_gate(jax.random.fold_in(rng, 0x9E37),
                                 layer_idx, cfg.num_layers, theta)
            y = jnp.where(gate, y, x)
            if aux_l is not None:
                # a dropped MoE layer contributed nothing — its balance
                # loss must not push its router (same rule as the flat
                # family, models/gpt.py)
                aux_l = jnp.where(gate, aux_l, 0.0)
        if moe:
            # alpha folded in here so the engine can just ADD the psum'd
            # scalar: loss = mean_m(ce_m) + sum(aux)/M.
            return y, cfg.moe_aux_alpha * aux_l
        return y

    # Final LN through flax's own LayerNorm (same impl/epsilon as the
    # non-pipelined GPT's ln_f) + the model's decode convention (tied einsum
    # or separate lm_head) + shared label shift, so the two loss paths
    # cannot drift.
    ln_f = nn.LayerNorm(epsilon=cfg.layer_norm_epsilon, dtype=jnp.float32)

    def head_fn(params, x, batch):
        from deepspeed_tpu.models.gpt import cross_entropy_with_ignore
        from deepspeed_tpu.ops.xent import fused_cross_entropy

        h = ln_f.apply({"params": params["head"]["ln_f"]}, x)
        labels = shift_labels(batch)
        mask = None
        if cfg.tie_embeddings:
            w, wt = params["embed"]["wte"], False
            if getattr(cfg, "padded_vocab", cfg.vocab_size) != cfg.vocab_size:
                from deepspeed_tpu.ops.embedding import vocab_pad_mask
                mask = vocab_pad_mask(cfg.padded_vocab, cfg.vocab_size)
        else:
            w, wt = params["head"]["lm_head"]["kernel"], True
        if not getattr(cfg, "fused_ce", True):
            # Honor the family's opt-out (ADVICE r3): exact fp32 logits +
            # stock log-softmax CE, as models/gpt.py's unfused branch.
            logits = jnp.einsum("bsd,vd->bsv" if not wt else "bsd,dv->bsv",
                                h.astype(cfg.dtype), w.astype(cfg.dtype),
                                preferred_element_type=jnp.float32)
            return cross_entropy_with_ignore(logits[..., :cfg.vocab_size],
                                             labels)
        return fused_cross_entropy(
            h.astype(cfg.dtype), w.astype(cfg.dtype), labels,
            w_transposed=wt, bias=mask, bias_grad=mask is None,
            logits_fp32=getattr(cfg, "fused_ce_fp32_logits", False))

    return PipeModel(embed_fn=embed_fn, block_fn=block_fn,
                     head_fn=head_fn, aux_fn=aux_fn, params=params,
                     num_blocks=cfg.num_layers, block_takes_layer_idx=True,
                     block_returns_aux=moe)
