"""Pipeline training engine.

The reference ``PipelineEngine`` (``deepspeed/runtime/pipe/engine.py:46``)
subclasses the data-parallel engine, replaces forward/backward/step with
``train_batch``/``eval_batch``, and host-executes the instruction schedule.
This engine keeps that public surface but compiles the whole pipelined step
— embed, 1F1B-equivalent microbatch pipeline over the ``pipe`` mesh axis,
head/loss, gradient accumulation, optimizer apply — into ONE jitted
program (see parallel/pipe/pipeline.py for the execution model).

ZeRO composition: like the reference (pipe/engine.py:56 forbids ZeRO-2+ with
pipelining) stages >= 2 are rejected — grads for the whole microbatch group
are produced by one backward here, so grad partitioning adds nothing; ZeRO-1
optimizer-state sharding composes fine.
"""

from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from deepspeed_tpu.config.config import DeepSpeedTPUConfig
from deepspeed_tpu.parallel.mesh import DATA_AXIS, PIPE_AXIS
from deepspeed_tpu.parallel.pipe.module import PipeModel
from deepspeed_tpu.parallel.pipe.pipeline import (pipeline_apply,
                                                  pipeline_apply_manual,
                                                  pipeline_spec)
from deepspeed_tpu.runtime.engine import TPUEngine, TrainState
from deepspeed_tpu.utils.logging import log_dist


class PipelineEngine(TPUEngine):
    """Engine for ``PipeModel``s. ``gradient_accumulation_steps`` plays the
    reference's ``micro_batches`` role: train_batch consumes GAS microbatches
    and pipelines them."""

    # This engine compiles its own step path — the ZeRO++ weight gather
    # (zero_optimization.zeropp) is unreachable here (and its stage >= 2
    # requirement collides with this engine's stage <= 1 rule anyway);
    # the base validation fails loudly instead of silently ignoring it.
    _supports_zeropp = False

    def __init__(self, pipe_model: PipeModel, config: DeepSpeedTPUConfig,
                 mesh: Optional[Mesh] = None, **kwargs):
        if config.zero_config.stage >= 2:
            raise ValueError(
                "ZeRO-2/3 are incompatible with pipeline parallelism "
                "(reference pipe/engine.py:56); use ZeRO-0/1")
        if config.pld.enabled and not pipe_model.block_takes_layer_idx:
            raise ValueError(
                "progressive_layer_drop under the PipelineEngine needs a "
                "PipeModel with block_takes_layer_idx=True (the per-layer "
                "drop gate consumes the global layer index; the in-tree "
                "gpt_pipe_model provides it) — this custom PipeModel "
                "would silently train with layer drop inert")
        self.pipe_model = pipe_model
        # Validate divisibility BEFORE state placement so the user sees a
        # clear error instead of a pjit sharding failure.
        pipe_size = (mesh.shape.get(PIPE_AXIS, 1) if mesh is not None
                     else config.mesh.pipe)
        pipe_model.check(pipe_size)
        base_specs = kwargs.pop("param_partition_specs", None)
        if base_specs is None:
            base_specs = jax.tree_util.tree_map(
                lambda _: PartitionSpec(), pipe_model.params)
            base_specs["blocks"] = pipeline_spec(pipe_model.params["blocks"])
        super().__init__(loss_fn=self._unused_loss_fn,
                         params=pipe_model.params, config=config, mesh=mesh,
                         param_partition_specs=base_specs, **kwargs)
        self.num_stages = self.mesh.shape.get(PIPE_AXIS, 1)
        self.micro_batches = self.gradient_accumulation_steps
        # This engine feeds the fleet step-time from its OUTER pipe_step
        # span (train_batch below); the base engine's inner train_step
        # note must stay off or the two would average.
        self._fleet_note_inner_span = False
        # An OOM crashdump from this engine names the pipeline shape —
        # the first thing a memory post-mortem of a staged schedule asks
        # (same label convention as the watchdog bracket below).
        self._memory_oom_label = (f"pipe_step[stages={self.num_stages},"
                                  f"mb={self.micro_batches}]")
        log_dist(f"PipelineEngine: stages={self.num_stages} "
                 f"micro_batches={self.micro_batches}", ranks=[0])

    @staticmethod
    def _unused_loss_fn(params, batch, rng):
        raise RuntimeError("PipelineEngine compiles its own loss path")

    def _make_pipe_loss(self):
        """loss(compute_params, batches, rng) through the GSPMD pipelined
        program (batches leaves [M, mb, ...]; rng=None ≡ eval/dropout off)."""
        pm = self.pipe_model
        gas = self.config.gradient_accumulation_steps
        mesh = self.mesh

        def pipe_loss(compute_params, batches, rng):
            def embed_one(b, i):
                k = None if rng is None else jax.random.fold_in(rng, i)
                return pm.embed_fn(compute_params, b, k)

            embeds = jax.vmap(embed_one)(batches, jnp.arange(gas))
            # aux presence is static (keyed on batch fields), so probe one
            # microbatch before vmapping.
            aux = None
            if pm.aux_fn is not None:
                first = jax.tree_util.tree_map(lambda x: x[0], batches)
                if pm.aux_fn(compute_params, first) is not None:
                    aux = jax.vmap(
                        lambda b: pm.aux_fn(compute_params, b))(batches)
            h = pipeline_apply(pm.block_fn, compute_params["blocks"], embeds,
                               mesh, aux=aux, rng=rng, num_microbatches=gas,
                               remat_blocks=True,
                               pass_layer_idx=pm.block_takes_layer_idx,
                               block_aux=pm.block_returns_aux)
            aux_total = None
            if pm.block_returns_aux:
                h, aux_total = h
            losses = jax.vmap(
                lambda hm, bm: pm.head_fn(compute_params, hm, bm))(h, batches)
            loss = jnp.mean(losses.astype(jnp.float32))
            if aux_total is not None:
                # aux_total sums every (microbatch, layer) contribution
                # (alpha folded in by block_fn); /gas gives the
                # per-microbatch mean matching the flat family's loss.
                loss = loss + aux_total / gas
            return loss

        return pipe_loss

    def _make_pipe_eval_step(self):
        precision = self.precision
        pipe_loss = self._make_pipe_loss()

        def eval_step(state: TrainState, batches):
            compute_params = precision.cast_params(state.params)
            return pipe_loss(compute_params, batches, None), None

        return eval_step

    # ------------------------------------------------------------------
    # 1-bit composition (BASELINE ladder final rung: pipe + ZeRO-1 +
    # OneBitAdam). The base engine's two-phase local-grad builder is reused;
    # these hooks add the pipe axis to the manual region and swap the GAS
    # scan for ONE pipelined fwd/bwd over all microbatches.
    # ------------------------------------------------------------------
    def _local_grad_axes(self):
        comp_axis, dense_axis, manual_axes = super()._local_grad_axes()
        if PIPE_AXIS in self.mesh.shape:
            manual_axes = set(manual_axes) | {PIPE_AXIS}
        return comp_axis, dense_axis, manual_axes

    def _local_grad_sq(self, grads):
        """Block grads are pipe-LOCAL shards (sum their squares over pipe);
        non-block grads are full gradients identical on every pipe rank
        after the psum fix-up (count once)."""
        from deepspeed_tpu.runtime.utils import global_norm

        if self.mesh.shape.get(PIPE_AXIS, 1) <= 1:
            return global_norm(grads) ** 2
        sq_blocks = global_norm(grads["blocks"]) ** 2
        rest = {k: v for k, v in grads.items() if k != "blocks"}
        sq_rest = global_norm(rest) ** 2 if rest else jnp.float32(0.0)
        return jax.lax.psum(sq_blocks, PIPE_AXIS) + sq_rest

    def _local_grad_forward_backward(self, comp_axis, dense_axis):
        """ONE pipelined fwd/bwd over all GAS microbatches inside the
        manual region. Gradient provenance over ``pipe``: the head/loss is
        computed (and masked) on the LAST stage only and the pipelined
        body keeps activations per stage, so embedding grads land on pipe
        rank 0, head grads on rank S-1, and block grads on their owning
        stage — one uniform psum-over-pipe then yields the full gradient
        for every non-block leaf (tied embeddings included: the psum
        collects the rank-0 embed part and the rank-(S-1) head part)."""
        gas = self.config.gradient_accumulation_steps
        pm = self.pipe_model
        stages = self.mesh.shape.get(PIPE_AXIS, 1)

        def run(compute_params, grad_acc, sub, scale, batches):
            def pipe_loss(cp):
                def embed_one(b, i):
                    k = jax.random.fold_in(sub, i)
                    return pm.embed_fn(cp, b, k)

                embeds = jax.vmap(embed_one)(batches, jnp.arange(gas))
                aux = None
                if pm.aux_fn is not None:
                    first = jax.tree_util.tree_map(lambda x: x[0], batches)
                    if pm.aux_fn(cp, first) is not None:
                        aux = jax.vmap(lambda b: pm.aux_fn(cp, b))(batches)
                h = pipeline_apply_manual(
                    pm.block_fn, cp["blocks"], embeds, aux, sub,
                    stages=stages, num_microbatches=gas, remat_blocks=True,
                    broadcast_output=False,
                    pass_layer_idx=pm.block_takes_layer_idx,
                    block_aux=pm.block_returns_aux)
                aux_total = None
                if pm.block_returns_aux:
                    h, aux_total = h
                if stages > 1:
                    last = jax.lax.axis_index(PIPE_AXIS) == stages - 1
                    # Zero invalid-rank activations BEFORE the head so the
                    # masked loss's zero cotangent multiplies finite values
                    # (garbage bf16 activations can reach inf; 0·inf = NaN
                    # in the backward).
                    h = jnp.where(last, h, jnp.zeros_like(h))
                losses = jax.vmap(
                    lambda hm, bm: pm.head_fn(cp, hm, bm))(h, batches)
                loss = jnp.mean(losses.astype(jnp.float32))
                if stages > 1:
                    loss = jax.lax.psum(jnp.where(last, loss, 0.0),
                                        PIPE_AXIS)
                if aux_total is not None:
                    # already psum'd over pipe inside the pipeline body
                    loss = loss + aux_total / gas
                return loss * scale, loss

            (_, loss), grads = jax.value_and_grad(
                pipe_loss, has_aux=True)(compute_params)
            grads = jax.tree_util.tree_map(
                lambda a, g: a + g.astype(a.dtype), grad_acc, grads)
            grads = jax.tree_util.tree_map(
                lambda g: g.astype(jnp.float32) / scale, grads)
            if stages > 1:
                grads = {k: (v if k == "blocks" else jax.tree_util.tree_map(
                    lambda g: jax.lax.psum(g, PIPE_AXIS), v))
                    for k, v in grads.items()}
            return grads, loss

        return run

    # ------------------------------------------------------------------
    def _build_step_fns(self) -> None:
        if getattr(self.optimizer, "needs_local_grads", False):
            self._build_local_grad_step_fns()
            # The base eval step calls loss_fn; pipelines evaluate through
            # the pipelined program instead.
            self._eval_step = jax.jit(self._make_pipe_eval_step())
            return
        cfg = self.config
        gas = cfg.gradient_accumulation_steps
        fp16 = cfg.fp16.enabled
        precision = self.precision
        mesh = self.mesh
        pm = self.pipe_model
        scaler = self.loss_scaler

        grad_shardings = jax.tree_util.tree_map(
            lambda s: NamedSharding(mesh, s), self.grad_specs)
        apply_step = self._make_apply_step()

        predivide = cfg.prescale_gradients
        raw_pipe_loss = self._make_pipe_loss()

        def pipe_loss(compute_params, batches, rng, scale):
            loss = raw_pipe_loss(compute_params, batches, rng)
            scaled = loss * scale
            if predivide:
                # Mirrors the base engine's pre-division, undone in
                # _make_apply_step's unscale.
                scaled = scaled / self.dp_size * cfg.gradient_predivide_factor
            return scaled, loss

        def train_step(state: TrainState, batches, lr):
            rng, sub = jax.random.split(state.rng)
            compute_params = precision.cast_params(state.params)
            scale = state.loss_scale.scale if fp16 else jnp.float32(1.0)
            grad_fn = jax.value_and_grad(pipe_loss, has_aux=True)
            (_, loss), grads = grad_fn(compute_params, batches, sub, scale)
            grads = jax.tree_util.tree_map(
                lambda a, g: a + g.astype(jnp.float32), state.grad_acc, grads)
            grads = jax.lax.with_sharding_constraint(grads, grad_shardings)
            state = state._replace(micro_step=state.micro_step + gas,
                                   grad_acc=grads, rng=rng)
            out = apply_step(state, lr)
            state, overflow, norm = out[0], out[1], out[2]
            if self.numerics is not None:
                # The shared apply computed the per-group stats (the
                # "blocks" group covers every pipeline stage).
                return state, loss, overflow, norm, {"groups": out[3]}
            return state, loss, overflow, norm

        def pipe_grad(compute_params, batches_, key, scale):
            grad_fn = jax.value_and_grad(pipe_loss, has_aux=True)
            (_, loss), grads = grad_fn(compute_params, batches_, key,
                                       scale)
            return loss, grads

        def train_step_hierarchical(state: TrainState, batches, lr):
            """The pipe grad path with the explicit hierarchical grad sync
            (comm/grad_sync.py): the whole pipelined fwd/bwd runs inside
            the manual={dcn} region on this slice's microbatch shards
            (microbatched=False — ONE grad_fn call consumes all
            microbatches), grads bucket + reduce-scatter over ICI,
            quantize-all-reduce over dcn, and feed the shared apply. Only
            reachable with pipeline stages == 1 (resolve_hierarchical
            rejects stages > 1: the pipelined program is its own manual
            region and shard_map regions do not nest on this jax) — the
            composition ladder for staged pipelines is documented in
            docs/PERFORMANCE.md."""
            plan = self.grad_sync_plan
            rng, sub = jax.random.split(state.rng)
            compute_params = precision.cast_params(state.params)
            scale = state.loss_scale.scale if fp16 else jnp.float32(1.0)
            grads, loss, qerr = plan.gas_sync(
                batches=batches, batch_spec=self.batch_spec,
                compute_params=compute_params, sub=sub, scale=scale,
                grad_fn=pipe_grad, microbatched=False)
            grads = jax.lax.with_sharding_constraint(grads, grad_shardings)
            state = state._replace(micro_step=state.micro_step + gas,
                                   grad_acc=grads, rng=rng)
            out = apply_step(state, lr)
            state, overflow, norm = out[0], out[1], out[2]
            if self.numerics is not None:
                aux = {"groups": out[3]}
                if qerr is not None:
                    aux["dcn_qerr"] = qerr
                return state, loss, overflow, norm, aux
            return state, loss, overflow, norm

        if self._grad_sync_on:
            from deepspeed_tpu.comm.grad_sync import (GradSyncPlan,
                                                      resolve_overlap)
            # gas=1: the pipelined fwd/bwd consumes all microbatches in
            # ONE grad_fn call, so the cross-microstep DCN overlap axis
            # is degenerate here; overlap still buys the readiness-
            # ordered per-bucket scatter chains.
            self.grad_sync_plan = GradSyncPlan(
                cfg.comm, mesh,
                grad_template=self.state.grad_acc,
                grad_specs=self.grad_specs,
                acc_dtype=self.grad_accum_dtype,
                ici_dtype=self._comm_dtype, gas=1,
                measure_quant_error=self.numerics is not None,
                overlap=resolve_overlap(cfg.comm))
            log_dist(self.grad_sync_plan.describe(), ranks=[0])
            train_step = train_step_hierarchical

        def eval_step(state: TrainState, batches):
            compute_params = precision.cast_params(state.params)
            _, loss = pipe_loss(compute_params, batches, None,
                                jnp.float32(1.0))
            return loss, None

        donate = (0,) if self._donate else ()
        self._train_step = jax.jit(train_step, donate_argnums=donate)
        self._eval_step = jax.jit(eval_step)
        self._micro_step = None
        self._apply_step = None

    # ------------------------------------------------------------------
    # Reference surface: pipeline engines only expose train/eval_batch
    # (pipe/engine.py:250; forward/backward raise there too).
    # ------------------------------------------------------------------
    def forward(self, batch):
        raise RuntimeError("PipelineEngine uses train_batch()/eval_batch() "
                           "only (reference pipe/engine.py)")

    def backward(self, loss=None, **kw):
        raise RuntimeError("PipelineEngine uses train_batch()/eval_batch() "
                           "only (reference pipe/engine.py)")

    def step(self):
        raise RuntimeError("PipelineEngine uses train_batch()/eval_batch() "
                           "only (reference pipe/engine.py)")

    def train_batch(self, batches) -> jax.Array:
        """One pipelined optimizer step over GAS microbatches. ``batches``
        leaves carry a leading microbatch dim == gradient_accumulation_steps
        (use ``split_batch`` to build them from a flat batch)."""
        tel = self.telemetry
        # Outermost watchdog bracket carries the pipeline shape: a trip
        # mid-pipe names the schedule (stages/microbatches) in the
        # crashdump, which is the first thing a hung-collective post-mortem
        # asks. The base engine's inner bracket is re-entrant (depth>1
        # no-ops), so the deadline covers the whole pipe_step.
        gr = self.guardrails
        if gr is not None:
            gr.step_begin(self.global_steps + 1,
                          label=f"pipe_step[stages={self.num_stages},"
                                f"mb={self.micro_batches}]")
        try:
            with tel.span("pipe_step", step=self.global_steps,
                          stages=self.num_stages,
                          micro_batches=self.micro_batches) as sp:
                loss = super().train_batch(batches)
        finally:
            if gr is not None:
                gr.step_end()
        if (self.fleet is not None and sp.duration
                and tel.tracer.sync_spans):
            # The OUTER pipe_step span brackets the whole pipelined step
            # (schedule + bubbles included) with sync'd boundaries — the
            # step time the fleet straggler detector should compare, since
            # a slow stage host stretches exactly this span. The base
            # engine's inner train_step note is disabled
            # (_fleet_note_inner_span) so the two spans are never
            # averaged; without sync_spans the span is dispatch-only and
            # the goodput fallback is used instead.
            self.fleet.note_step_time(sp.duration)
        if tel.enabled and self.num_stages > 1:
            # Per-stage bubble: in a GPipe/1F1B schedule every stage idles
            # (S-1) microbatch slots of the (M + S - 1)-slot step, so the
            # analytic bubble fraction is uniform across stages; with
            # sync'd spans the pipe_step duration is the real step wall
            # time and frac * duration is each stage's idle time.
            frac = (self.num_stages - 1) / (self.micro_batches
                                            + self.num_stages - 1)
            reg = tel.registry
            reg.gauge("pipe/bubble_fraction").set(frac,
                                                  step=self.global_steps)
            if sp.duration:
                reg.gauge("pipe/bubble_time_sec").set(
                    sp.duration * frac, step=self.global_steps)
                if self.goodput is not None:
                    # Analytic bubble seconds as a goodput auxiliary gauge
                    # (goodput/pipe_bubble_sec): schedule-idle time hiding
                    # INSIDE productive_step — not part of the wall-clock
                    # partition, but exactly the slice the overlap work on
                    # the ROADMAP would claw back.
                    self.goodput.note_aux("pipe_bubble_sec",
                                          sp.duration * frac)
        if self.global_steps % self.steps_per_print == 0:
            log_dist(f"step={self.global_steps} loss={float(loss):.4f}",
                     ranks=[0])
        return loss

    def eval_batch(self, batches):
        batches = self.put_batch(batches, leading_gas_dim=True)
        loss, _ = self._eval_step(self.state, batches)
        return loss

    def split_batch(self, batch):
        """Reshape a flat batch into GAS microbatches (leading dim)."""
        gas = self.micro_batches

        def split(x):
            x = np.asarray(x)
            if x.shape[0] % gas:
                raise ValueError(f"batch dim {x.shape[0]} not divisible by "
                                 f"micro_batches={gas}")
            return x.reshape(gas, x.shape[0] // gas, *x.shape[1:])

        return jax.tree_util.tree_map(split, batch)
