"""Pipeline training engine.

The reference ``PipelineEngine`` (``deepspeed/runtime/pipe/engine.py:46``)
subclasses the data-parallel engine, replaces forward/backward/step with
``train_batch``/``eval_batch``, and host-executes the instruction schedule.
This engine keeps that public surface but compiles the whole pipelined step
— embed, 1F1B-equivalent microbatch pipeline over the ``pipe`` mesh axis,
head/loss, gradient accumulation, optimizer apply — into ONE jitted
program (see parallel/pipe/pipeline.py for the execution model).

ZeRO composition: like the reference (pipe/engine.py:56 forbids ZeRO-2+ with
pipelining) stages >= 2 are rejected — grads for the whole microbatch group
are produced by one backward here, so grad partitioning adds nothing; ZeRO-1
optimizer-state sharding composes fine.
"""

from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from deepspeed_tpu.config.config import DeepSpeedTPUConfig
from deepspeed_tpu.parallel.mesh import DATA_AXIS, PIPE_AXIS
from deepspeed_tpu.parallel.pipe.module import PipeModel
from deepspeed_tpu.parallel.pipe.pipeline import pipeline_apply, pipeline_spec
from deepspeed_tpu.runtime.engine import TPUEngine, TrainState
from deepspeed_tpu.utils.logging import log_dist


class PipelineEngine(TPUEngine):
    """Engine for ``PipeModel``s. ``gradient_accumulation_steps`` plays the
    reference's ``micro_batches`` role: train_batch consumes GAS microbatches
    and pipelines them."""

    def __init__(self, pipe_model: PipeModel, config: DeepSpeedTPUConfig,
                 mesh: Optional[Mesh] = None, **kwargs):
        if config.zero_config.stage >= 2:
            raise ValueError(
                "ZeRO-2/3 are incompatible with pipeline parallelism "
                "(reference pipe/engine.py:56); use ZeRO-0/1")
        self.pipe_model = pipe_model
        # Validate divisibility BEFORE state placement so the user sees a
        # clear error instead of a pjit sharding failure.
        pipe_size = (mesh.shape.get(PIPE_AXIS, 1) if mesh is not None
                     else config.mesh.pipe)
        pipe_model.check(pipe_size)
        base_specs = kwargs.pop("param_partition_specs", None)
        if base_specs is None:
            base_specs = jax.tree_util.tree_map(
                lambda _: PartitionSpec(), pipe_model.params)
            base_specs["blocks"] = pipeline_spec(pipe_model.params["blocks"])
        super().__init__(loss_fn=self._unused_loss_fn,
                         params=pipe_model.params, config=config, mesh=mesh,
                         param_partition_specs=base_specs, **kwargs)
        self.num_stages = self.mesh.shape.get(PIPE_AXIS, 1)
        self.micro_batches = self.gradient_accumulation_steps
        log_dist(f"PipelineEngine: stages={self.num_stages} "
                 f"micro_batches={self.micro_batches}", ranks=[0])

    @staticmethod
    def _unused_loss_fn(params, batch, rng):
        raise RuntimeError("PipelineEngine compiles its own loss path")

    # ------------------------------------------------------------------
    def _build_step_fns(self) -> None:
        cfg = self.config
        gas = cfg.gradient_accumulation_steps
        fp16 = cfg.fp16.enabled
        precision = self.precision
        mesh = self.mesh
        pm = self.pipe_model
        scaler = self.loss_scaler

        grad_shardings = jax.tree_util.tree_map(
            lambda s: NamedSharding(mesh, s), self.grad_specs)
        apply_step = self._make_apply_step()

        predivide = cfg.prescale_gradients

        def pipe_loss(compute_params, batches, rng, scale):
            # batches leaves: [M, mb, ...]; rng=None ≡ eval (dropout off).
            def embed_one(b, i):
                k = None if rng is None else jax.random.fold_in(rng, i)
                return pm.embed_fn(compute_params, b, k)

            embeds = jax.vmap(embed_one)(batches, jnp.arange(gas))
            # aux presence is static (keyed on batch fields), so probe one
            # microbatch before vmapping.
            aux = None
            if pm.aux_fn is not None:
                first = jax.tree_util.tree_map(lambda x: x[0], batches)
                if pm.aux_fn(compute_params, first) is not None:
                    aux = jax.vmap(
                        lambda b: pm.aux_fn(compute_params, b))(batches)
            h = pipeline_apply(pm.block_fn, compute_params["blocks"], embeds,
                               mesh, aux=aux, rng=rng, num_microbatches=gas,
                               remat_blocks=True)
            losses = jax.vmap(
                lambda hm, bm: pm.head_fn(compute_params, hm, bm))(h, batches)
            loss = jnp.mean(losses.astype(jnp.float32))
            scaled = loss * scale
            if predivide:
                # Mirrors the base engine's pre-division, undone in
                # _make_apply_step's unscale.
                scaled = scaled / self.dp_size * cfg.gradient_predivide_factor
            return scaled, loss

        def train_step(state: TrainState, batches, lr):
            rng, sub = jax.random.split(state.rng)
            compute_params = precision.cast_params(state.params)
            scale = state.loss_scale.scale if fp16 else jnp.float32(1.0)
            grad_fn = jax.value_and_grad(pipe_loss, has_aux=True)
            (_, loss), grads = grad_fn(compute_params, batches, sub, scale)
            grads = jax.tree_util.tree_map(
                lambda a, g: a + g.astype(jnp.float32), state.grad_acc, grads)
            grads = jax.lax.with_sharding_constraint(grads, grad_shardings)
            state = state._replace(micro_step=state.micro_step + gas,
                                   grad_acc=grads, rng=rng)
            state, overflow, norm = apply_step(state, lr)
            return state, loss, overflow, norm

        def eval_step(state: TrainState, batches):
            compute_params = precision.cast_params(state.params)
            _, loss = pipe_loss(compute_params, batches, None,
                                jnp.float32(1.0))
            return loss, None

        donate = (0,) if self._donate else ()
        self._train_step = jax.jit(train_step, donate_argnums=donate)
        self._eval_step = jax.jit(eval_step)
        self._micro_step = None
        self._apply_step = None

    # ------------------------------------------------------------------
    # Reference surface: pipeline engines only expose train/eval_batch
    # (pipe/engine.py:250; forward/backward raise there too).
    # ------------------------------------------------------------------
    def forward(self, batch):
        raise RuntimeError("PipelineEngine uses train_batch()/eval_batch() "
                           "only (reference pipe/engine.py)")

    def backward(self, loss=None, **kw):
        raise RuntimeError("PipelineEngine uses train_batch()/eval_batch() "
                           "only (reference pipe/engine.py)")

    def step(self):
        raise RuntimeError("PipelineEngine uses train_batch()/eval_batch() "
                           "only (reference pipe/engine.py)")

    def train_batch(self, batches) -> jax.Array:
        """One pipelined optimizer step over GAS microbatches. ``batches``
        leaves carry a leading microbatch dim == gradient_accumulation_steps
        (use ``split_batch`` to build them from a flat batch)."""
        loss = super().train_batch(batches)
        if self.global_steps % self.steps_per_print == 0:
            log_dist(f"step={self.global_steps} loss={float(loss):.4f}",
                     ranks=[0])
        return loss

    def eval_batch(self, batches):
        batches = self.put_batch(batches, leading_gas_dim=True)
        loss, _ = self._eval_step(self.state, batches)
        return loss

    def split_batch(self, batch):
        """Reshape a flat batch into GAS microbatches (leading dim)."""
        gas = self.micro_batches

        def split(x):
            x = np.asarray(x)
            if x.shape[0] % gas:
                raise ValueError(f"batch dim {x.shape[0]} not divisible by "
                                 f"micro_batches={gas}")
            return x.reshape(gas, x.shape[0] // gas, *x.shape[1:])

        return jax.tree_util.tree_map(split, batch)
