"""Sequence/context parallelism — ring attention and Ulysses all-to-all.

The reference snapshot has NO sequence parallelism (its `slice_parallel` is
just an alias of the model axis, pipe/topology.py:446; long sequences are
served by block-sparse attention only). This module adds the real
capability the way TPUs want it:

- **Ring attention**: q/k/v stay sharded over the ``sequence`` mesh axis;
  K/V chunks rotate around the ring with ``ppermute`` over ICI while each
  device accumulates flash-style online-softmax partials for its local Q
  chunk. Memory per device is O(S/n); the K/V rotation overlaps with the
  per-chunk attention compute under XLA's scheduler.
- **Ulysses all-to-all**: ``all_to_all`` reshards [B, S/n, H, D] ->
  [B, S, H/n, D] so each device runs FULL-sequence attention for H/n heads
  (the Pallas flash kernel applies directly), then reshards back. Two
  all-to-alls per call; requires heads % n == 0.

Both run inside a shard_map that is manual over ``sequence`` ONLY, so data
parallel batch sharding and ZeRO placement continue to compose via GSPMD.
Softmax statistics and cross-chunk merges are fp32.
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from deepspeed_tpu.utils.jax_compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from deepspeed_tpu.parallel.mesh import SEQUENCE_AXIS

NEG_INF = -1e30


def _require_native_shard_map(what: str) -> None:
    """Old jax's XLA CPU backend hard-aborts (C-level) compiling these
    partial-manual sequence programs — raise a catchable error instead of
    letting the process die (utils/jax_compat.py)."""
    from deepspeed_tpu.utils.jax_compat import NATIVE_SHARD_MAP
    if not NATIVE_SHARD_MAP:
        raise NotImplementedError(
            f"{what} over a sequence axis > 1 requires a jax with native "
            "shard_map; this jax's XLA backend aborts compiling the "
            "partial-manual program")


def _chunk_attention_partial(q, k, v, scale, mask):
    """Unnormalised attention of one (q-chunk, kv-chunk) pair.

    q: [B, Sq, H, D]; k,v: [B, Sk, H, D]; mask: [Sq, Sk] bool or None.
    Returns (acc [B,Sq,H,D] fp32, m [B,H,Sq] fp32 rowmax, l [B,H,Sq] fp32
    rowsum) — the flash-attention partial statistics for later merging.
    """
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if mask is not None:
        logits = jnp.where(mask[None, None], logits, NEG_INF)
    m = jnp.max(logits, axis=-1)                      # [B,H,Sq]
    p = jnp.exp(logits - m[..., None])
    l = jnp.sum(p, axis=-1)
    acc = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    return acc, m, l


def _merge_partials(carry, update):
    """Online-softmax merge of two partial results."""
    acc0, m0, l0 = carry
    acc1, m1, l1 = update
    m = jnp.maximum(m0, m1)
    a0 = jnp.exp(m0 - m)
    a1 = jnp.exp(m1 - m)
    acc = (acc0 * a0.transpose(0, 2, 1)[..., None] +
           acc1 * a1.transpose(0, 2, 1)[..., None])
    return acc, m, l0 * a0 + l1 * a1


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                   mesh: Mesh,
                   causal: bool = False,
                   softmax_scale: Optional[float] = None,
                   axis: str = SEQUENCE_AXIS) -> jax.Array:
    """Ring attention over the ``sequence`` axis.

    q/k/v: [B, S, H, D] GLOBAL shapes (jit-level); under the hood each
    sequence rank holds S/n. Returns [B, S, H, D].
    """
    n = mesh.shape.get(axis, 1)
    scale = softmax_scale if softmax_scale is not None else 1.0 / (q.shape[-1] ** 0.5)
    if n == 1:
        from deepspeed_tpu.ops.transformer.attention import xla_attention

        return xla_attention(q, k, v, causal=causal, softmax_scale=scale)
    s_global = q.shape[1]
    if s_global % n:
        raise ValueError(f"seq {s_global} not divisible by sequence axis {n}")
    _require_native_shard_map("ring attention")
    chunk = s_global // n
    orig_dtype = q.dtype

    def ring_fn(q_c, k_c, v_c):
        rank = jax.lax.axis_index(axis)
        shift = [(i, (i + 1) % n) for i in range(n)]
        q32 = q_c.astype(jnp.float32)
        q_pos = rank * chunk + jax.lax.broadcasted_iota(
            jnp.int32, (chunk, chunk), 0)

        def hop(carry, r):
            acc_m_l, kc, vc = carry
            src = (rank - r) % n
            if causal:
                k_pos = src * chunk + jax.lax.broadcasted_iota(
                    jnp.int32, (chunk, chunk), 1)
                mask = q_pos >= k_pos
            else:
                mask = None
            part = _chunk_attention_partial(q32, kc.astype(jnp.float32),
                                            vc.astype(jnp.float32),
                                            scale, mask)
            acc_m_l = _merge_partials(acc_m_l, part)
            kc = jax.lax.ppermute(kc, axis, shift)
            vc = jax.lax.ppermute(vc, axis, shift)
            return (acc_m_l, kc, vc), None

        b, _, h, d = q_c.shape
        init = ((jnp.zeros((b, chunk, h, d), jnp.float32),
                 jnp.full((b, h, chunk), NEG_INF, jnp.float32),
                 jnp.zeros((b, h, chunk), jnp.float32)), k_c, v_c)
        (final, _, _), _ = jax.lax.scan(hop, init, jnp.arange(n))
        acc, _, l = final
        l_safe = jnp.maximum(l, 1e-30).transpose(0, 2, 1)[..., None]
        return (acc / l_safe).astype(orig_dtype)

    seq_spec = P(None, SEQUENCE_AXIS, None, None)
    mapped = shard_map(
        ring_fn, mesh=mesh,
        in_specs=(seq_spec, seq_spec, seq_spec),
        out_specs=seq_spec,
        axis_names={axis},
        check_vma=False,
    )
    # Partial-manual shard_map only traces under jit; the wrapper inlines
    # when an outer jit is active and compiles standalone in eager use.
    return jax.jit(mapped)(q, k, v)


def ulysses_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                      mesh: Mesh,
                      causal: bool = False,
                      softmax_scale: Optional[float] = None,
                      attention_impl: str = "xla",
                      axis: str = SEQUENCE_AXIS) -> jax.Array:
    """Ulysses-style all-to-all sequence parallelism.

    Reshards seq-sharded q/k/v to head-sharded, runs full-sequence attention
    per head group (optionally with the Pallas flash kernel), reshards back.
    """
    n = mesh.shape.get(axis, 1)
    scale = softmax_scale if softmax_scale is not None else 1.0 / (q.shape[-1] ** 0.5)
    from deepspeed_tpu.ops.transformer.attention import attention as attn

    if n == 1:
        return attn(q, k, v, causal=causal, softmax_scale=scale,
                    impl=attention_impl)
    h = q.shape[2]
    if h % n:
        raise ValueError(f"{h} heads not divisible by sequence axis {n}")
    _require_native_shard_map("Ulysses attention")

    def ulysses_fn(q_c, k_c, v_c):
        # [B, S/n, H, D] -> [B, S, H/n, D]: gather seq, scatter heads.
        def seq_to_head(x):
            return jax.lax.all_to_all(x, axis, split_axis=2, concat_axis=1,
                                      tiled=True)

        def head_to_seq(x):
            return jax.lax.all_to_all(x, axis, split_axis=1, concat_axis=2,
                                      tiled=True)

        qh, kh, vh = seq_to_head(q_c), seq_to_head(k_c), seq_to_head(v_c)
        out = attn(qh, kh, vh, causal=causal, softmax_scale=scale,
                   impl=attention_impl)
        return head_to_seq(out)

    seq_spec = P(None, SEQUENCE_AXIS, None, None)
    mapped = shard_map(
        ulysses_fn, mesh=mesh,
        in_specs=(seq_spec, seq_spec, seq_spec),
        out_specs=seq_spec,
        axis_names={axis},
        check_vma=False,
    )
    return jax.jit(mapped)(q, k, v)
