"""Device-mesh construction.

TPU-native replacement for the reference's process-group plumbing
(``deepspeed/utils/distributed.py:12`` ``init_distributed`` and the
``mpu``-supplied groups the engine consumes at ``runtime/engine.py:672-683``):
instead of NCCL groups we build one ``jax.sharding.Mesh`` with named axes and
let pjit/XLA lower collectives onto ICI/DCN.

Axis order is chosen so the *data* axis is innermost (fastest-varying over
physically adjacent chips) — gradient reduce-scatter/all-gather is the hot
collective and should ride ICI neighbours; pipe is outermost since stage p2p
traffic is the lightest.
"""

import os
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from deepspeed_tpu.utils.logging import log_dist

# Canonical axis names used across the framework.
DATA_AXIS = "data"
MODEL_AXIS = "model"
PIPE_AXIS = "pipe"
SEQUENCE_AXIS = "sequence"
EXPERT_AXIS = "expert"
# Slice-outer data-parallel axis for multi-slice / multi-pod topologies:
# collectives over it ride DCN (slow inter-slice links), everything else
# rides ICI. The reference's analogue is its Ethernet-cluster NCCL/MPI
# backends (runtime/comm/nccl.py:47) — the 1-bit optimizers compress over
# exactly this axis, and ZeRO sharding deliberately stays on the ICI-inner
# `data` axis (SURVEY §2.5 TPU-native row).
DCN_AXIS = "dcn"

ALL_AXES = (DCN_AXIS, PIPE_AXIS, EXPERT_AXIS, DATA_AXIS, SEQUENCE_AXIS,
            MODEL_AXIS)


def init_distributed(dist_backend: str = "xla",
                     coordinator_address: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None,
                     timeout: Optional[int] = None) -> None:
    """Multi-host rendezvous — the ``init_distributed`` analogue.

    Single-process usage (one host, or tests) needs no call; multi-host pods
    call this once per host before building a mesh. Environment discovery
    mirrors the reference's env-var path (MASTER_ADDR/RANK/WORLD_SIZE,
    reference utils/distributed.py:54): our launcher exports
    DSTPU_COORDINATOR / DSTPU_NUM_PROCS / DSTPU_RANK.
    """
    # NB: must not touch jax.devices()/process_count() here — any backend
    # query initialises the local runtime and jax.distributed.initialize
    # would then be too late.
    from deepspeed_tpu.utils.jax_compat import distributed_is_initialized
    if distributed_is_initialized():
        return
    coordinator_address = coordinator_address or os.environ.get("DSTPU_COORDINATOR")
    if coordinator_address is None and "MASTER_ADDR" in os.environ:
        port = os.environ.get("MASTER_PORT", "29500")
        coordinator_address = f"{os.environ['MASTER_ADDR']}:{port}"
    if coordinator_address is None:
        return  # single-host
    num_processes = num_processes or int(
        os.environ.get("DSTPU_NUM_PROCS", os.environ.get("WORLD_SIZE", "1")))
    process_id = process_id if process_id is not None else int(
        os.environ.get("DSTPU_RANK", os.environ.get("RANK", "0")))
    # Multi-host rendezvous through the shared jittered-backoff helper
    # (guardrails/retry.py): on a pod restart the coordinator host may come
    # up seconds after the workers, and one flaky DNS answer should not
    # kill an otherwise healthy incarnation. DSTPU_INIT_RETRIES=0 restores
    # fail-fast.
    from deepspeed_tpu.guardrails.retry import retry_call

    def rendezvous():
        try:
            jax.distributed.initialize(
                coordinator_address=coordinator_address,
                num_processes=num_processes,
                process_id=process_id)
        except Exception:
            # A failed connect leaves global_state.client/service assigned,
            # and re-entering initialize() would then raise "should only be
            # called once" — masking the real error and making every retry
            # dead. shutdown() resets that state (no-op when nothing
            # started), so the next attempt is a genuine re-rendezvous.
            try:
                jax.distributed.shutdown()
            except Exception:  # noqa: BLE001 — best-effort reset
                pass
            raise

    retry_call(rendezvous,
               max_retries=int(os.environ.get("DSTPU_INIT_RETRIES", "3")),
               base=1.0, max_delay=15.0,
               describe="jax.distributed.initialize")
    log_dist(f"jax.distributed initialised: {num_processes} processes "
             f"@ {coordinator_address}", ranks=[0])


@dataclass(frozen=True)
class MeshShape:
    dcn: int = 1
    pipe: int = 1
    expert: int = 1
    data: int = 1
    sequence: int = 1
    model: int = 1

    @property
    def world(self) -> int:
        return (self.dcn * self.pipe * self.expert * self.data *
                self.sequence * self.model)

    def dims(self) -> Dict[str, int]:
        return {DCN_AXIS: self.dcn, PIPE_AXIS: self.pipe,
                EXPERT_AXIS: self.expert, DATA_AXIS: self.data,
                SEQUENCE_AXIS: self.sequence, MODEL_AXIS: self.model}


def build_mesh(data: int = -1,
               model: int = 1,
               pipe: int = 1,
               sequence: int = 1,
               expert: int = 1,
               slices: int = 1,
               devices: Optional[Sequence] = None) -> Mesh:
    """Build the framework mesh. ``data=-1`` infers from the device count.

    All axes are always present (size-1 axes are free); downstream sharding
    specs can therefore reference any axis unconditionally.

    ``slices > 1`` builds a DCN-aware hierarchical mesh: the outermost
    ``dcn`` axis spans TPU slices/pods (slow links), every other axis stays
    inside a slice (ICI). On real multi-slice hardware the device order
    comes from ``mesh_utils.create_hybrid_device_mesh`` (slice-local
    ICI topology inside, slice id outside); elsewhere (virtual CPU meshes,
    single-slice) a plain slice-major reshape stands in.
    """
    devices = list(devices if devices is not None else jax.devices())
    ndev = len(devices)
    fixed = model * pipe * sequence * expert * slices
    if data == -1:
        if ndev % fixed != 0:
            raise ValueError(
                f"{ndev} devices not divisible by "
                f"slices×model×pipe×seq×expert={fixed}")
        data = ndev // fixed
    shape = MeshShape(dcn=slices, pipe=pipe, expert=expert, data=data,
                      sequence=sequence, model=model)
    if shape.world != ndev:
        raise ValueError(f"mesh {shape.dims()} needs {shape.world} devices, have {ndev}")
    dims = shape.dims()
    full = tuple(dims[a] for a in ALL_AXES)
    from jax.experimental import mesh_utils

    dev_array = None
    if slices > 1:
        try:
            ici = (1,) + full[1:]
            dcn = (slices,) + (1,) * (len(full) - 1)
            dev_array = mesh_utils.create_hybrid_device_mesh(
                ici, dcn, devices=devices)
        except Exception:
            dev_array = None    # no slice metadata (CPU / single slice)
    if dev_array is None:
        # Use hardware-aware device ordering when available so the
        # innermost mesh axes land on ICI-adjacent chips.
        try:
            dev_array = mesh_utils.create_device_mesh(full, devices=devices)
        except Exception:
            dev_array = np.array(devices).reshape(full)
    return Mesh(dev_array, ALL_AXES)


def single_device_mesh() -> Mesh:
    return build_mesh(data=1)


# Ambient mesh: ops that need mesh-aware collectives (ring/Ulysses
# attention selected by a model config string) read it when no mesh is
# passed explicitly. The engine registers its mesh at construction.
_DEFAULT_MESH: Optional[Mesh] = None


def set_default_mesh(mesh: Optional[Mesh]) -> None:
    global _DEFAULT_MESH
    _DEFAULT_MESH = mesh


def get_default_mesh() -> Optional[Mesh]:
    return _DEFAULT_MESH


def data_sharding(mesh: Mesh, batch_axes: Sequence[str] = (DATA_AXIS,)) -> NamedSharding:
    """Sharding for input batches: leading dim split over data(-like) axes."""
    return NamedSharding(mesh, PartitionSpec(tuple(batch_axes)))


def axes_size(mesh_shape, axes) -> int:
    """Product of the named axes' sizes in a mesh-shape mapping (absent
    axes count 1). The ONE definition of how an axes tuple maps to a
    shard count — the ZeRO partitioner, ParamGatherPlan's wire model /
    qerr weighting, and the memory ledger must all agree on it (accepts
    both ``mesh.shape`` and plain dicts)."""
    n = 1
    for a in axes:
        n *= int(mesh_shape.get(a, 1))
    return n


def data_like_axes(mesh: Mesh) -> tuple:
    """The mesh's data-parallel axes with size > 1 (dcn-outer + ici
    data), falling back to ``(data,)`` on a trivial mesh — the ONE
    definition of "data-like" shared by the sparse-gradient exchange and
    the engine surgery."""
    axes = tuple(a for a in (DCN_AXIS, DATA_AXIS)
                 if mesh.shape.get(a, 1) > 1)
    return axes or (DATA_AXIS,)


def moe_dispatch_axes(mesh: Mesh) -> tuple:
    """Manual axes of the explicit MoE dispatch region (moe/dispatch.py):
    the data-like token axes plus ``expert``. Tokens are sharded over the
    full tuple inside the region — expert parallelism is carved out of
    the data-parallel world, exactly the reference's expert process
    groups — and the all-to-all runs over ``expert`` within each
    data-like column."""
    return data_like_axes(mesh) + (EXPERT_AXIS,)


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())


def mesh_axis_size(mesh: Mesh, axis: str) -> int:
    return mesh.shape.get(axis, 1)


def local_batch_ranks(mesh: Mesh) -> List[int]:
    """Global data-parallel positions handled by this process (for samplers)."""
    # With jit + NamedSharding, each process feeds its addressable shards;
    # data loading uses process_index/process_count granularity.
    return [jax.process_index()]
