"""Process/axis topology.

Capability parity with the reference's ``deepspeed/runtime/pipe/topology.py``
(``ProcessTopology`` :12, ``PipeDataParallelTopology`` :235,
``PipeModelDataParallelTopology`` :246, ``PipelineParallelGrid`` :252).

On TPU the cartesian rank grid *is* the ``jax.sharding.Mesh``; this module
keeps the pure-Python coordinate algebra (axis naming, rank<->coord mapping,
filtered rank groups) because the pipeline scheduler, checkpoint resharding,
and grid bookkeeping all consume it, and it must work without devices present
(e.g. offline checkpoint tools).
"""

from collections import namedtuple
from itertools import product
from typing import Dict, List, Sequence, Tuple


class ProcessTopology:
    """An N-dimensional cartesian grid of ranks with named axes.

    Axes are ordered major..minor: the *last* axis varies fastest with rank,
    matching the reference's axes order semantics (topology.py:25-40).
    """

    def __init__(self, axes: Sequence[str], dims: Sequence[int]):
        if len(axes) != len(dims):
            raise ValueError("axes and dims must have equal length")
        if len(set(axes)) != len(axes):
            raise ValueError(f"duplicate axis names: {axes}")
        self.axes = list(axes)
        self.dims = list(int(d) for d in dims)
        for a, d in zip(self.axes, self.dims):
            if d < 1:
                raise ValueError(f"axis {a} must have dim >= 1, got {d}")
        self.ProcessCoord = namedtuple("ProcessCoord", self.axes)
        self.mapping: Dict[Tuple[int, ...], int] = {}
        ranges = [range(d) for d in self.dims]
        for global_rank, coord in enumerate(product(*ranges)):
            key = self.ProcessCoord(*coord)
            self.mapping[key] = global_rank

    def get_rank(self, **coord_kwargs) -> int:
        if len(coord_kwargs) != len(self.axes):
            raise ValueError(f"get_rank() requires all axes {self.axes}")
        key = self.ProcessCoord(**coord_kwargs)
        if key not in self.mapping:
            raise ValueError(f"coord {coord_kwargs} out of range for dims {self.dims}")
        return self.mapping[key]

    def get_axis_names(self) -> List[str]:
        return list(self.axes)

    def get_rank_repr(self, rank: int, omit_axes: Sequence[str] = ("data",),
                      inner_sep: str = "_", outer_sep: str = "-") -> str:
        """String like 'pipe_00-model_00' used in checkpoint filenames."""
        omit_axes = list(omit_axes)
        axes = [a for a in self.axes if a not in omit_axes]
        names = []
        for ax in axes:
            ax_rank = getattr(self.get_coord(rank=rank), ax)
            names.append(f"{ax}{inner_sep}{ax_rank:02d}")
        return outer_sep.join(names)

    def get_dim(self, axis: str) -> int:
        if axis not in self.axes:
            return 0
        return self.dims[self.axes.index(axis)]

    def get_coord(self, rank: int):
        for coord, idx in self.mapping.items():
            if idx == rank:
                return coord
        raise ValueError(f"rank {rank} not in topology")

    def get_axis_comm_lists(self, axis: str) -> List[List[int]]:
        """Rank lists for communication along ``axis``, one per fixed setting
        of the other axes (reference topology.py:139)."""
        if axis not in self.axes:
            return []
        other_axes = [a for a in self.axes if a != axis]
        lists = []
        ranges = [range(self.get_dim(a)) for a in other_axes]
        for combo in product(*ranges):
            fixed = dict(zip(other_axes, combo))
            ranks = [self.get_rank(**{axis: i, **fixed}) for i in range(self.get_dim(axis))]
            lists.append(ranks)
        return lists

    def filter_match(self, **filter_kwargs) -> List[int]:
        """All ranks whose coords match the given axis=value filters."""

        def _match(coord):
            return all(getattr(coord, k) == v for k, v in filter_kwargs.items())

        return sorted(rank for coord, rank in self.mapping.items() if _match(coord))

    def get_axis_list(self, axis: str, idx: int) -> List[int]:
        return self.filter_match(**{axis: idx})

    def world_size(self) -> int:
        size = 1
        for d in self.dims:
            size *= d
        return size

    def __str__(self) -> str:
        return f"ProcessTopology(axes={self.axes}, dims={self.dims})"


class PipeDataParallelTopology(ProcessTopology):
    """Pipeline × data hybrid; data-parallel groups span adjacent ranks so the
    heavy DP gradient traffic stays on the fastest links (topology.py:235)."""

    def __init__(self, num_pp: int, num_dp: int):
        super().__init__(axes=["pipe", "data"], dims=[num_pp, num_dp])


class PipeModelDataParallelTopology(ProcessTopology):
    """3D pipe × data × model topology (topology.py:246)."""

    def __init__(self, num_pp: int, num_mp: int, num_dp: int):
        super().__init__(axes=["pipe", "data", "model"], dims=[num_pp, num_dp, num_mp])


class PipelineParallelGrid:
    """Axis-group bookkeeping for a pipeline run (reference topology.py:252).

    The reference builds torch process groups here; on TPU the collectives are
    mesh-axis-addressed inside jit, so this grid only answers the pure
    rank-arithmetic questions (stage ids, peer stage ranks, group membership)
    that the pipeline module/engine and checkpoint code ask.
    """

    def __init__(self, topology: ProcessTopology, global_rank: int = 0):
        self._topo = topology
        self.global_rank = global_rank
        self.world_size = topology.world_size()

        self.data_parallel_size = max(self._topo.get_dim("data"), 1)
        self.pipe_parallel_size = max(self._topo.get_dim("pipe"), 1)
        self.model_parallel_size = max(self._topo.get_dim("model"), 1)
        assert self.world_size == (
            self.data_parallel_size * self.pipe_parallel_size * self.model_parallel_size)

        coord = self._topo.get_coord(self.global_rank)
        self.stage_id = getattr(coord, "pipe", 0)
        self.data_parallel_id = getattr(coord, "data", 0)
        self.model_parallel_id = getattr(coord, "model", 0) if "model" in self._topo.axes else 0
        # "slice parallel" is the reference's alias for the model axis
        # (topology.py:446-455).
        self.slice_parallel_id = self.model_parallel_id

        self.pp_group = self._topo.filter_match(data=self.data_parallel_id) \
            if "data" in self._topo.axes else list(range(self.world_size))
        self.dp_group = self._topo.filter_match(pipe=self.stage_id) \
            if "pipe" in self._topo.axes else list(range(self.world_size))

        self.p2p_matrix = self._build_p2p_pairs()

    def _build_p2p_pairs(self) -> List[Tuple[int, int]]:
        """Adjacent-stage (send, recv) rank pairs incl. the wraparound pair used
        for tied-embedding grads (reference topology.py:373-389)."""
        pairs = []
        if "pipe" not in self._topo.axes:
            return pairs
        for lists in self._topo.get_axis_comm_lists("pipe"):
            for i, rank in enumerate(lists):
                nxt = lists[(i + 1) % len(lists)]
                pairs.append((rank, nxt))
        return pairs

    # --- stage arithmetic ------------------------------------------------
    def get_stage_id(self) -> int:
        return self.stage_id

    def get_data_parallel_id(self) -> int:
        return self.data_parallel_id

    def get_pipe_parallel_rank(self) -> int:
        return self.stage_id

    def get_pipe_parallel_world_size(self) -> int:
        return self.pipe_parallel_size

    def get_data_parallel_rank(self) -> int:
        return self.data_parallel_id

    def get_data_parallel_world_size(self) -> int:
        return self.data_parallel_size

    def get_model_parallel_rank(self) -> int:
        return self.model_parallel_id

    def get_model_parallel_world_size(self) -> int:
        return self.model_parallel_size

    def get_global_rank(self) -> int:
        return self.global_rank

    def is_first_stage(self) -> bool:
        return self.stage_id == 0

    def is_last_stage(self) -> bool:
        return self.stage_id == self.pipe_parallel_size - 1

    def stage_to_global(self, stage_id: int, **kwargs) -> int:
        coord = self._topo.get_coord(self.global_rank)
        d = coord._asdict()
        d.update(kwargs)
        d["pipe"] = stage_id
        return self._topo.get_rank(**d)

    @property
    def topology(self) -> ProcessTopology:
        return self._topo
