"""Live elasticity — in-process shrink/grow on preemption, step-boundary
rejoin, and goodput-driven straggler eviction (docs/RESILIENCE.md "Live
elasticity").

The supervisor tier (PR 1) already survives a preemption — by paying a
full cold restart: process death, interpreter + jax re-import, engine
reconstruction, checkpoint deserialize, reshard. The goodput reports say
``init_restore`` dominates that bill. This module removes it for the case
that actually dominates preemptible fleets — the *advance-warned* slice
preemption:

- **shrink** — the platform's advance warning (SIGTERM inside a
  configurable grace window) is *caught*, not obeyed: at the next step
  boundary the coordinator drains in-flight work, pulls the newest
  verdict-clean state (live engine state when the guardrails verdict is
  clean, else the guardrails ``SnapshotRing``, else the newest on-disk
  resilience checkpoint), asks the elastic ladder for the largest valid
  world fitting the surviving chips
  (:func:`deepspeed_tpu.elasticity.world_change_plan` — the global batch
  is a ladder property, so convergence never changes), rebuilds the mesh
  and jitted step functions, and re-places the gathered host state through
  the existing ``install_state_arrays`` reshard path. Same pid, no
  ``init_restore``, no supervisor round-trip.
- **rejoin** — a returning slice is re-admitted at the next snapshot
  boundary through a small supervisor-coordinated rendezvous: the
  returning side writes a rejoin request file (host, chips,
  ``elastic_config_hash``) into the run dir; the coordinator polls it at
  ``check_interval_steps`` cadence, re-checks the hash (two worlds may
  differ in chips but must agree on batch math), and grows back. The
  world-change epoch is stamped into the goodput run manifest and every
  resilience checkpoint manifest, so post-mortem tools can line attempts
  up against world changes.
- **evict** — the fleet layer's persistent-straggler verdicts
  (telemetry/fleet.py, PR 6 ``Supervisor.straggler_hosts``) finally close
  their loop: a straggler is evicted only when the goodput cost model
  (:func:`evaluate_eviction` — measured ``straggler_sec`` rate × horizon
  vs. measured reshard cost) says shrinking wins. Every decision — taken
  or declined — is logged as an ``elastic/*`` instant naming the host and
  the evidence, and recorded in the run manifest.

Zero-overhead contract (the house rule): ``elasticity.live`` defaults off
and :func:`build_elastic` then returns ``None`` — no signal handler is
installed, the engine's step-boundary hook is one attribute check, and
the lowered step program is bit-identical to an elasticity-less config
(tests/test_elastic.py pins all three).
"""

import contextlib
import json
import os
import signal
import time
from typing import Any, Dict, List, Optional, Tuple

from deepspeed_tpu.utils.logging import logger

# Test/simulation seam: names the victim slice of the NEXT advance
# warning. On a real deployment each host knows its own slice id — the
# warning lands on the doomed hosts — but the single-process CPU
# reproduction receives its own SIGTERM and must be told which slice the
# platform is taking.
PREEMPT_SLICE_ENV = "DSTPU_PREEMPT_SLICE"

# The rendezvous file a returning slice's supervisor writes into the run
# dir; the coordinator admits it at the next snapshot boundary.
REJOIN_REQUEST_FILE = "elastic_rejoin.json"

# Every metric tag this module can emit — gauges plus the decision
# instants — pinned against docs/OBSERVABILITY.md in BOTH directions by
# tests/test_doc_lint.py, like GOODPUT_METRIC_TAGS.
ELASTIC_METRIC_TAGS = frozenset({
    "elastic/world_size",
    "elastic/reshards",
    "elastic/reshard_sec",
    "elastic/evictions",
    # decision/event instants (trace markers, same namespace)
    "elastic/preempt_warned",
    "elastic/shrink",
    "elastic/rejoin",
    "elastic/rejoin_refused",
    "elastic/evict",
})


class LiveElasticityError(RuntimeError):
    """The coordinator could not complete a world change."""


# ---------------------------------------------------------------------------
# Eviction cost model
# ---------------------------------------------------------------------------

def evaluate_eviction(lost_sec_per_step: float,
                      horizon_steps: int,
                      reshard_cost_sec: float,
                      min_gain_factor: float = 2.0) -> Dict[str, Any]:
    """The goodput cost model behind every eviction decision: keeping the
    straggler costs ``lost_sec_per_step`` on every future step (the fleet
    runs at the slowest host's pace — telemetry/fleet.py books the same
    number as ``goodput/straggler_sec``); evicting costs one reshard.
    Evict iff the projected loss over ``horizon_steps`` exceeds
    ``min_gain_factor`` × the reshard cost — the factor absorbs the
    throughput the smaller world gives up and the chance the straggler
    recovers on its own. Pure arithmetic, unit-tested against synthetic
    fleets."""
    projected = max(0.0, float(lost_sec_per_step)) * max(int(horizon_steps), 0)
    cost = max(0.0, float(reshard_cost_sec))
    return {
        "lost_sec_per_step": float(lost_sec_per_step),
        "horizon_steps": int(horizon_steps),
        "projected_gain_sec": projected,
        "reshard_cost_sec": cost,
        "min_gain_factor": float(min_gain_factor),
        "evict": projected > cost * float(min_gain_factor),
    }


# ---------------------------------------------------------------------------
# Rejoin rendezvous (file-based: the supervisor and the coordinator share
# the run dir; nothing else is assumed about the control plane)
# ---------------------------------------------------------------------------

def request_rejoin(run_dir: str, host: str, chips: int,
                   elastic_config_hash: str = "") -> str:
    """Written by the returning slice's supervisor: ask the running job to
    re-admit ``chips`` chips at its next snapshot boundary."""
    path = os.path.join(run_dir, REJOIN_REQUEST_FILE)
    os.makedirs(run_dir, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump({"host": host, "chips": int(chips),
                   "elastic_config_hash": elastic_config_hash,
                   "requested_wall": time.time()}, f)
    os.replace(tmp, path)
    return path


def read_rejoin_request(run_dir: str) -> Optional[Dict[str, Any]]:
    path = os.path.join(run_dir, REJOIN_REQUEST_FILE)
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def clear_rejoin_request(run_dir: str) -> None:
    with contextlib.suppress(OSError):
        os.remove(os.path.join(run_dir, REJOIN_REQUEST_FILE))


# ---------------------------------------------------------------------------
# The coordinator
# ---------------------------------------------------------------------------

class ElasticCoordinator:
    """Per-engine live-elasticity driver.

    The engine owns exactly one call site — :meth:`step_boundary` after
    every committed optimizer step (one attribute check when nothing is
    pending) — plus :meth:`install`/:meth:`close` around its lifetime.
    Everything expensive (drain, gather, rebuild) happens only on an
    actual world change.
    """

    def __init__(self, engine, lcfg, run_dir: Optional[str] = None):
        self.engine = engine
        self.cfg = lcfg
        self.run_dir = run_dir
        self.epoch = 0
        # Slice-major device inventory of the FULL mesh, captured at
        # construction: _full_slice_devices[k] is slice k's device list.
        mesh = engine.mesh
        from deepspeed_tpu.parallel.mesh import DCN_AXIS
        n_slices = mesh.shape.get(DCN_AXIS, 1)
        dev_array = mesh.devices
        per_slice = dev_array.reshape(n_slices, -1)
        self._full_slice_devices: List[List[Any]] = [
            list(per_slice[k].ravel()) for k in range(n_slices)]
        self._full_slices = n_slices
        self._lost_slices: set = set()
        self.world_size = int(mesh.size)
        self._preempt_pending = False
        self._warned_at: Optional[float] = None
        self._victim_slice: Optional[int] = None
        self._prev_handler = None
        self._installed = False
        self.reshards = 0
        self.evictions = 0
        self.last_reshard_sec: Optional[float] = None
        self._shrink_step_attempt: Optional[int] = None
        self.eviction_decisions: List[Dict[str, Any]] = []
        # Deployment seam: maps a fleet-flagged straggler host to the
        # slice to evict. None => decisions are logged/stamped but no
        # shrink is executed (the supervisor-level restart policy still
        # acts on them).
        self.host_slice_fn = None
        self._evict_decided: set = set()
        self._grow_pending = False

    # -- lifecycle -------------------------------------------------------
    def install(self) -> "ElasticCoordinator":
        """Install the SIGTERM advance-warning handler. Only called when
        ``elasticity.live`` is enabled — a disabled config never touches
        signal dispositions (the zero-overhead contract)."""
        try:
            self._prev_handler = signal.signal(signal.SIGTERM,
                                               self._on_sigterm)
            self._installed = True
        except ValueError:
            # Not the main thread: the platform warning cannot reach a
            # python handler here anyway.
            logger.warning(
                "live elasticity: cannot install SIGTERM handler off the "
                "main thread — advance warnings will kill the process "
                "(the supervisor cold-restart path still applies)")
        return self

    def close(self) -> None:
        if self._installed:
            with contextlib.suppress(ValueError):
                signal.signal(signal.SIGTERM,
                              self._prev_handler or signal.SIG_DFL)
            self._installed = False

    # -- the advance warning --------------------------------------------
    def _on_sigterm(self, signum, frame) -> None:
        now = time.monotonic()
        if self._preempt_pending:
            # Second SIGTERM while one warning is still pending: the
            # platform is out of patience — restore the previous
            # disposition and die like an unwarned preemption.
            logger.warning("live elasticity: second SIGTERM before the "
                           "pending shrink completed — giving up")
            signal.signal(signal.SIGTERM,
                          self._prev_handler or signal.SIG_DFL)
            os.kill(os.getpid(), signal.SIGTERM)
            return
        self._preempt_pending = True
        self._warned_at = now
        self._victim_slice = self._resolve_victim()
        logger.warning(
            "live elasticity: preemption advance warning caught (SIGTERM; "
            "grace %.1fs, victim slice %s) — will drain and shrink "
            "in-process at the next step boundary",
            self.cfg.grace_seconds, self._victim_slice)
        tel = self.engine.telemetry
        if tel is not None and tel.enabled:
            tel.instant("elastic/preempt_warned",
                        slice=self._victim_slice,
                        grace_seconds=self.cfg.grace_seconds)

    def _resolve_victim(self) -> int:
        env = os.environ.get(PREEMPT_SLICE_ENV)
        if env is not None and env != "":
            return int(env)
        fp = getattr(self.engine, "fault_plan", None)
        if fp is not None and fp.slice_preempt_slice is not None:
            return int(fp.slice_preempt_slice)
        surviving = [k for k in range(self._full_slices)
                     if k not in self._lost_slices]
        return surviving[-1] if surviving else 0

    # -- the per-step hook ----------------------------------------------
    def step_boundary(self, engine) -> None:
        """Called by the engine after every committed step. Steady state:
        a couple of attribute checks; world changes happen only here —
        between steps, never mid-collective."""
        if self._preempt_pending:
            self._preempt_pending = False
            grace_left = (self.cfg.grace_seconds
                          - (time.monotonic() - (self._warned_at or 0.0)))
            if grace_left <= 0:
                logger.warning(
                    "live elasticity: grace window (%.1fs) already "
                    "elapsed before the step boundary — shrinking anyway "
                    "(the platform may kill us mid-reshard)",
                    self.cfg.grace_seconds)
            self.shrink(self._victim_slice, cause="preemption",
                        grace_left=max(0.0, grace_left))
            return
        if self._lost_slices:
            fp = getattr(engine, "fault_plan", None)
            if self._grow_pending or (
                    fp is not None and fp.should_rejoin(
                        engine.step_attempts, self._shrink_step_attempt)):
                self._grow_pending = False
                self.grow(cause="rejoin")
                return
            if self._rendezvous_due(engine):
                return  # grow (or refusal) already handled inside
        if self.cfg.eviction.enabled and engine.fleet is not None:
            self.maybe_evict(engine)

    def _rendezvous_due(self, engine) -> bool:
        """Poll the rejoin request file at the snapshot-boundary cadence;
        admit (grow) on a hash-matching request, refuse loudly otherwise.
        Returns True when a request was consumed either way."""
        if not self.run_dir:
            return False
        if engine.global_steps % self.cfg.check_interval_steps != 0:
            return False
        req = read_rejoin_request(self.run_dir)
        if req is None:
            return False
        want = getattr(engine, "elastic_hash", "")
        got = req.get("elastic_config_hash", "")
        if want and want != got:
            # A missing/empty hash is refused too: the writer is an
            # EXTERNAL supervisor, and admitting an unverified slice
            # would silently waive the batch-math contract the doc
            # promises is re-checked.
            logger.warning(
                "live elasticity: rejoin request from %s REFUSED — "
                "elastic config hash %r does not match the running "
                "ladder %s (different batch math would change the "
                "trajectory mid-run; the request must carry the "
                "ladder's elastic_config_hash)",
                req.get("host"), got[:12], want[:12])
            tel = engine.telemetry
            if tel is not None and tel.enabled:
                tel.instant("elastic/rejoin_refused", host=req.get("host"),
                            theirs=got[:12], ours=want[:12])
            clear_rejoin_request(self.run_dir)
            return True
        clear_rejoin_request(self.run_dir)
        self.grow(cause="rejoin", host=req.get("host"))
        return True

    # -- shrink / grow ---------------------------------------------------
    def request_shrink(self, victim_slice: Optional[int] = None) -> None:
        """Programmatic shrink request (platform integrations, chaos
        soaks): behaves exactly like a caught advance warning — the world
        change lands at the next step boundary."""
        self._preempt_pending = True
        self._warned_at = time.monotonic()
        self._victim_slice = (victim_slice if victim_slice is not None
                              else self._resolve_victim())

    def request_rejoin_now(self) -> None:
        """Programmatic rejoin request: grow back at the next step
        boundary (the file-based rendezvous is the cross-process path)."""
        self._grow_pending = True

    def shrink(self, victim_slice: Optional[int], *,
               cause: str = "preemption", grace_left: float = 0.0,
               host: Optional[str] = None) -> None:
        victim = (int(victim_slice) if victim_slice is not None
                  else self._resolve_victim())
        self._lost_slices.add(victim)
        surviving = [k for k in range(self._full_slices)
                     if k not in self._lost_slices]
        chips = sum(len(self._full_slice_devices[k]) for k in surviving)
        if chips == 0:
            self._drain_and_exit(
                f"live elasticity: slice {victim} preempted and no "
                "capacity survives — draining to disk and exiting with "
                "the preemption-warned rc")
        try:
            self._reshard(surviving, cause=cause, detail={
                "slice": victim, "grace_left_sec": round(grace_left, 3),
                **({"host": host} if host else {})})
        except Exception as e:  # noqa: BLE001 — no valid world / rebuild
            # failure: the warned preemption still ends the process, but
            # with state drained and the distinct rc.
            self._drain_and_exit(
                f"live elasticity: in-process shrink after losing slice "
                f"{victim} failed ({e}) — draining to disk and exiting "
                "with the preemption-warned rc")

    def grow(self, *, cause: str = "rejoin",
             host: Optional[str] = None) -> None:
        returned = sorted(self._lost_slices)
        surviving = list(range(self._full_slices))
        try:
            self._reshard(surviving, cause=cause, detail={
                "returned_slices": returned,
                **({"host": host} if host else {})})
        except Exception as e:  # noqa: BLE001 — a failed rejoin must not
            # poison the training loop OR the coordinator's world view:
            # the shrunken world keeps training, the slices stay marked
            # lost (a later rejoin request can retry), and the refusal is
            # loud.
            logger.error(
                "live elasticity: rejoin of slices %s FAILED (%s) — "
                "continuing at the current world %d; a new rejoin "
                "request can retry", returned, e, self.world_size)
            tel = self.engine.telemetry
            if tel is not None and tel.enabled:
                tel.instant("elastic/rejoin_refused",
                            returned_slices=returned, error=str(e))
            return
        self._lost_slices.clear()

    def _reshard(self, surviving_slices: List[int], *, cause: str,
                 detail: Dict[str, Any]) -> None:
        """The one world-change implementation shrink/grow/evict share:
        drain → clean-state gather → ladder solve → engine rebuild →
        telemetry + manifest stamps."""
        import jax

        from deepspeed_tpu.elasticity import world_change_plan

        engine = self.engine
        t0 = time.monotonic()
        gp = engine.goodput
        measure = (gp.measure("elastic_reshard") if gp is not None
                   else contextlib.nullcontext())
        gr = engine.guardrails
        if gr is not None and gr.watchdog is not None:
            # A reshard (recompile included) is not a hung step; the
            # deadline must not convert it into a watchdog kill — same
            # rule as rollback recovery.
            gr.watchdog.suspend()
        with measure:
            # Drain: every dispatched program referencing the old mesh
            # must complete before its buffers are gathered/re-placed.
            jax.block_until_ready(engine.state)
            arrays, meta, source = self._clean_state(engine)
            flat_devices = [d for k in surviving_slices
                            for d in self._full_slice_devices[k]]
            ds_config = {"elasticity": dict(engine.config.elasticity)}
            world, micro, gas = world_change_plan(ds_config,
                                                  len(flat_devices))
            slices, devices = self._solve_slices(surviving_slices, world)
            engine._elastic_rebuild(devices=devices, slices=slices,
                                    micro_batch=micro, gas=gas,
                                    arrays=arrays, meta=meta)
        dt = time.monotonic() - t0
        self.reshards += 1
        self.epoch += 1
        engine.elastic_epoch = self.epoch
        self.last_reshard_sec = dt
        self.world_size = world
        self._shrink_step_attempt = (None if cause == "rejoin"
                                     else engine.step_attempts)
        logger.warning(
            "live elasticity: %s reshard complete in %.3fs — world %d "
            "(slices %s, micro %d, gas %d, state from %s, epoch %d)",
            cause, dt, world, slices, micro, gas, source, self.epoch)
        self._emit(engine, cause=cause, detail={**detail,
                                                "state_source": source,
                                                "reshard_sec": round(dt, 4)})
        if gp is not None:
            gp.note_world_change({
                "epoch": self.epoch, "step": int(engine.global_steps),
                "world_size": world, "cause": cause,
                "reshard_sec": round(dt, 4), **detail})
            gp.write_manifest()

    def _solve_slices(self, surviving_slices: List[int],
                      world: int) -> Tuple[int, List[Any]]:
        """Fit ``world`` chips onto whole surviving slices: the largest
        slice count whose per-slice share divides evenly (a slice is the
        DCN failure/billing domain — never split one across the ladder's
        rung). Falls back to a single flat slice of the first ``world``
        devices when nothing divides (degenerate ladders)."""
        cfg = self.engine.config
        fixed = (cfg.mesh.model * cfg.mesh.pipe * cfg.mesh.sequence
                 * cfg.mesh.expert)
        for s in range(len(surviving_slices), 0, -1):
            if world % (s * fixed):
                continue
            per_slice = world // s
            take = surviving_slices[:s]
            if all(len(self._full_slice_devices[k]) >= per_slice
                   for k in take):
                devices = [d for k in take
                           for d in self._full_slice_devices[k][:per_slice]]
                return s, devices
        flat = [d for k in surviving_slices
                for d in self._full_slice_devices[k]]
        return 1, flat[:world]

    def _clean_state(self, engine) -> Tuple[Dict[str, Any], Dict[str, Any],
                                            str]:
        """The newest VERDICT-CLEAN host state: the live engine state when
        the last guardrails verdict (if any) was not a spike; else the
        guardrails SnapshotRing's newest entry; else the newest complete
        on-disk resilience checkpoint. Raises when nothing clean exists —
        resharding poisoned state would just carry the poison to the new
        world."""
        from deepspeed_tpu.resilience.checkpoint import (find_restorable,
                                                         snapshot_engine)

        gr = engine.guardrails
        suspect = (gr is not None and gr.last_verdict is not None
                   and bool(gr.last_verdict))
        if not suspect:
            snap = snapshot_engine(engine)
            return dict(snap.arrays), snap.meta, "live"
        if gr.ring is not None and gr.ring.newest() is not None:
            snap = gr.ring.newest()
            logger.warning(
                "live elasticity: last verdict was a spike — resharding "
                "from the snapshot ring (step %s), not live state",
                snap.meta.get("step"))
            return dict(snap.arrays), snap.meta, "ring"
        rcfg = getattr(engine.config, "resilience", None)
        if rcfg is not None and rcfg.enabled:
            found = find_restorable(rcfg.checkpoint.dir)
            if found is not None:
                _, manifest, arrays, _ = found
                logger.warning(
                    "live elasticity: resharding from on-disk checkpoint "
                    "step %s (no clean in-memory state)",
                    manifest.get("step"))
                return arrays, manifest, "disk"
        raise LiveElasticityError(
            "no verdict-clean state to reshard from (live state is "
            "spike-suspect, the snapshot ring is empty and no complete "
            "on-disk checkpoint exists)")

    def _drain_and_exit(self, message: str) -> None:
        engine = self.engine
        logger.error(message)
        with contextlib.suppress(Exception):
            if engine.ckpt_manager is not None:
                engine.save_checkpoint_async()
                engine.ckpt_manager.wait()
        if engine.goodput is not None:
            engine.goodput.finalize(exit_rc=self.cfg.exit_code)
        os._exit(self.cfg.exit_code)

    # -- eviction --------------------------------------------------------
    def maybe_evict(self, engine) -> Optional[Dict[str, Any]]:
        """Close the straggler loop: when the fleet layer marks a host
        persistent, run the goodput cost model; evict its slice when the
        model approves AND a host→slice mapping exists. Each host gets
        ONE decision per run (persistent verdicts repeat every flush —
        re-deciding would spam the manifest)."""
        fleet = engine.fleet
        verdict = getattr(fleet, "last_verdict", None)
        if not verdict or not verdict.get("persistent"):
            return None
        host = verdict["host"]
        if host in self._evict_decided:
            return None
        self._evict_decided.add(host)
        reshard_cost = (self.last_reshard_sec
                        if self.last_reshard_sec is not None
                        else self.cfg.eviction.assumed_reshard_sec)
        decision = evaluate_eviction(
            verdict.get("lost_sec_per_step", 0.0),
            self.cfg.eviction.horizon_steps,
            reshard_cost,
            self.cfg.eviction.min_gain_factor)
        decision.update(host=host, zscore=round(verdict.get("zscore", 0.0), 3),
                        step=int(engine.global_steps),
                        reshard_cost_measured=self.last_reshard_sec
                        is not None)
        self.eviction_decisions.append(decision)
        tel = engine.telemetry
        if tel is not None and tel.enabled:
            tel.instant("elastic/evict", **{
                k: decision[k] for k in ("host", "zscore", "evict",
                                         "projected_gain_sec",
                                         "reshard_cost_sec", "step")})
        if engine.goodput is not None:
            engine.goodput.note_eviction(decision)
        if not decision["evict"]:
            logger.warning(
                "live elasticity: straggler %s (z=%.2f) NOT evicted — "
                "projected gain %.1fs over %d steps < %.1fx reshard cost "
                "%.1fs", host, decision["zscore"],
                decision["projected_gain_sec"], decision["horizon_steps"],
                decision["min_gain_factor"], decision["reshard_cost_sec"])
            return decision
        slice_id = (self.host_slice_fn(host)
                    if self.host_slice_fn is not None else None)
        if slice_id is None:
            logger.warning(
                "live elasticity: eviction of straggler %s approved "
                "(gain %.1fs > %.1fx cost %.1fs) but no host→slice "
                "mapping is configured — decision recorded for the "
                "supervisor restart policy", host,
                decision["projected_gain_sec"],
                decision["min_gain_factor"], decision["reshard_cost_sec"])
            return decision
        logger.warning(
            "live elasticity: EVICTING straggler %s (slice %d, z=%.2f): "
            "projected gain %.1fs over %d steps > %.1fx reshard cost "
            "%.1fs", host, slice_id, decision["zscore"],
            decision["projected_gain_sec"], decision["horizon_steps"],
            decision["min_gain_factor"], decision["reshard_cost_sec"])
        self.evictions += 1
        self.shrink(slice_id, cause="eviction", host=host)
        return decision

    # -- telemetry -------------------------------------------------------
    def _emit(self, engine, *, cause: str, detail: Dict[str, Any]) -> None:
        tel = engine.telemetry
        if tel is None or not tel.enabled:
            return
        step = int(engine.global_steps)
        reg = tel.registry
        reg.gauge("elastic/world_size").set(self.world_size, step=step,
                                            epoch=self.epoch)
        reg.gauge("elastic/reshards").set(self.reshards, step=step)
        reg.gauge("elastic/reshard_sec").set(
            self.last_reshard_sec or 0.0, step=step, cause=cause)
        reg.gauge("elastic/evictions").set(self.evictions, step=step)
        name = ("elastic/shrink" if cause in ("preemption", "eviction")
                else "elastic/rejoin")
        tel.instant(name, cause=cause, world_size=self.world_size,
                    epoch=self.epoch, step=step, **detail)
        tel.flush()


def build_elastic(engine) -> Optional[ElasticCoordinator]:
    """``None`` unless ``elasticity.live`` is enabled — the engine's hook
    gates on ``is None`` and NO signal handler is installed (the
    zero-overhead contract, same shape as guardrails/goodput/fleet)."""
    lcfg = getattr(engine.config, "elasticity_live", None)
    if lcfg is None or not lcfg.enabled:
        return None
    tcfg = engine.config.telemetry
    run_dir = tcfg.dir if getattr(tcfg, "enabled", False) else None
    return ElasticCoordinator(engine, lcfg, run_dir=run_dir).install()
