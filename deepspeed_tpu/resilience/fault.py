"""Deterministic fault injection.

TPU pods are preemptible by design; the recovery path (resilience/checkpoint
+ supervisor) is only trustworthy if failure is *injectable* so tier-1 CPU
tests exercise it. A :class:`FaultPlan` describes, deterministically, the
faults a run must survive:

- ``preempt_at_step``   — SIGTERM the process right after optimizer step k
  completes (the maintenance-event preemption shape: the job dies between
  steps, not mid-collective);
- ``ckpt_write_errors`` — the first N checkpoint shard writes raise
  ``OSError`` (flaky persistent-disk / GCS path), exercising the writer's
  retry + exponential backoff;
- ``corrupt_shard_at_step`` — after the checkpoint for step k commits, one
  shard file's bytes are flipped (torn write / bitrot), exercising manifest
  digest verification and the fall-back to the previous complete manifest;
- ``nan_loss_at_step`` / ``nan_loss_steps`` — the batches for a window of
  steps are NaN-poisoned before dispatch (a corrupted input shard / bad
  preprocessing push), so the loss and gradients genuinely go non-finite
  through the real step — exercising the guardrails detector + in-memory
  rollback (guardrails/);
- ``hang_at_step`` / ``hang_seconds`` — the step stalls mid-flight (the
  deadlocked-collective shape), exercising the guardrails step watchdog's
  diagnostics dump + distinct-rc exit and the supervisor's immediate
  restart;
- ``slice_preempt_at_step`` / ``slice_preempt_slice`` /
  ``preempt_grace_seconds`` — the multi-slice preemption ADVANCE WARNING:
  SIGTERM delivered to self at step-attempt k *without* resetting the
  handler, so the live-elasticity coordinator (resilience/elastic.py) can
  catch it and shrink in-process within the grace window (contrast
  ``preempt_at_step``, which restores SIG_DFL first — the no-warning
  death shape). ``slice_preempt_slice`` names the victim slice (default:
  the highest surviving index);
- ``rejoin_after_steps`` — the preempted slice "returns" this many step
  attempts after the shrink, exercising the step-boundary rejoin path
  deterministically;
- ``serve_decode_fault_at_step`` / ``serve_decode_fault_count`` — the
  SERVING chaos events (serving/resilience.py; docs/SERVING.md "Serving
  under failure"): the decode/spec dispatch raises ``RuntimeError`` for
  a window of decode **dispatch attempts** (a monotonic count the
  engine keeps — retries advance it, so ``count=1`` exercises the
  retry-only path and ``count > max_retries + 1`` forces the
  rebuild+replay path deterministically);
- ``serve_slow_step_at_step`` / ``serve_slow_step_seconds`` /
  ``serve_slow_step_count`` — injected straggler decode steps
  (``time.sleep`` inside the decode timing window), exercising the
  slow-step anomaly detector, the degradation ladder and the
  ``run_until_complete`` wall-clock timeout;
- ``serve_storm_at_step`` / ``serve_storm_requests`` — a request-storm
  burst at one serving step boundary (duplicates of the last submitted
  request through the normal ``submit()`` path), exercising the
  admission gate / load shedding under overload.

The numeric/hang faults are keyed on **step attempts** (a monotonic count
of dispatched steps) rather than ``global_steps``: a guardrails rollback
rewinds the step counter, and keying on it would re-poison the retried
window forever — a data-borne fault follows the data stream, which only
moves forward.

The plan comes from the config block (``resilience.fault_injection``) with an
environment override (``DSTPU_FAULT_PLAN``, a JSON object merged over the
block) so the supervisor / test driver can inject without editing configs.

Faults are scoped to a restart *attempt*: injection is active only while the
supervisor-maintained ``DSTPU_RESUME_ATTEMPT`` (default 0) is <=
``max_attempt`` (default 0), so an injected death does not re-kill every
resumed incarnation — the restarted job runs the same plan object but sees
it inert, exactly like a real one-off preemption.
"""

import json
import os
import signal
from dataclasses import dataclass
from typing import Any, Dict, Optional

from deepspeed_tpu.utils.logging import logger

FAULT_PLAN_ENV = "DSTPU_FAULT_PLAN"
RESUME_ATTEMPT_ENV = "DSTPU_RESUME_ATTEMPT"


@dataclass
class FaultPlan:
    """Deterministic fault schedule for one training incarnation."""

    preempt_at_step: Optional[int] = None
    ckpt_write_errors: int = 0
    corrupt_shard_at_step: Optional[int] = None
    nan_loss_at_step: Optional[int] = None
    nan_loss_steps: int = 1
    hang_at_step: Optional[int] = None
    hang_seconds: float = 3600.0
    slice_preempt_at_step: Optional[int] = None
    slice_preempt_slice: Optional[int] = None
    preempt_grace_seconds: float = 30.0
    rejoin_after_steps: Optional[int] = None
    serve_decode_fault_at_step: Optional[int] = None
    serve_decode_fault_count: int = 1
    serve_slow_step_at_step: Optional[int] = None
    serve_slow_step_seconds: float = 0.05
    serve_slow_step_count: int = 1
    serve_storm_at_step: Optional[int] = None
    serve_storm_requests: int = 8
    max_attempt: int = 0

    def __post_init__(self):
        if self.ckpt_write_errors < 0:
            raise ValueError("ckpt_write_errors must be >= 0")
        if self.nan_loss_steps < 1:
            raise ValueError("nan_loss_steps must be >= 1")
        if self.hang_seconds <= 0:
            raise ValueError("hang_seconds must be > 0")
        if self.preempt_grace_seconds <= 0:
            raise ValueError("preempt_grace_seconds must be > 0")
        if self.rejoin_after_steps is not None and self.rejoin_after_steps < 1:
            raise ValueError("rejoin_after_steps must be >= 1")
        if self.serve_decode_fault_count < 1:
            raise ValueError("serve_decode_fault_count must be >= 1")
        if self.serve_slow_step_seconds <= 0:
            raise ValueError("serve_slow_step_seconds must be > 0")
        if self.serve_slow_step_count < 1:
            raise ValueError("serve_slow_step_count must be >= 1")
        if self.serve_storm_requests < 1:
            raise ValueError("serve_storm_requests must be >= 1")
        self._io_errors_left = int(self.ckpt_write_errors)

    # ------------------------------------------------------------------
    @classmethod
    def resolve(cls, config_block: Optional[Dict[str, Any]] = None,
                env: Optional[Dict[str, str]] = None) -> Optional["FaultPlan"]:
        """Config block + ``DSTPU_FAULT_PLAN`` env override -> plan (or None
        when no fault is scheduled / a later restart attempt is running)."""
        env = os.environ if env is None else env
        d = dict(config_block or {})
        override = env.get(FAULT_PLAN_ENV)
        if override:
            try:
                d.update(json.loads(override))
            except (ValueError, TypeError) as e:
                raise ValueError(
                    f"{FAULT_PLAN_ENV} is not a JSON object: {e}") from e
        if not d:
            return None
        known = {f for f in cls.__dataclass_fields__}
        unknown = set(d) - known
        if unknown:
            raise ValueError(
                f"unknown fault_injection keys {sorted(unknown)}; "
                f"expected a subset of {sorted(known)}")
        plan = cls(**{k: d[k] for k in d})
        attempt = int(env.get(RESUME_ATTEMPT_ENV, "0") or 0)
        if attempt > plan.max_attempt:
            logger.info("FaultPlan inert on resume attempt %d (max_attempt="
                        "%d): %s", attempt, plan.max_attempt, plan)
            return None
        return plan

    # ------------------------------------------------------------------
    def take_io_error(self) -> bool:
        """One checkpoint shard write is about to happen; True = inject."""
        if self._io_errors_left > 0:
            self._io_errors_left -= 1
            return True
        return False

    def should_preempt(self, global_step: int) -> bool:
        return (self.preempt_at_step is not None
                and global_step == self.preempt_at_step)

    def should_corrupt(self, global_step: int) -> bool:
        return (self.corrupt_shard_at_step is not None
                and global_step == self.corrupt_shard_at_step)

    def should_nan_loss(self, step_attempt: int) -> bool:
        """Poison the batch for this step attempt? Active for the window
        ``[nan_loss_at_step, nan_loss_at_step + nan_loss_steps)``."""
        return (self.nan_loss_at_step is not None
                and self.nan_loss_at_step <= step_attempt
                < self.nan_loss_at_step + self.nan_loss_steps)

    def poison_batch(self, batch):
        """NaN-fill every floating leaf of a host batch pytree (the
        corrupted-input-shard shape: the step runs for real and its loss /
        grads genuinely go non-finite)."""
        import numpy as np

        def leaf(x):
            x = np.asarray(x)
            if np.issubdtype(x.dtype, np.floating):
                return np.full_like(x, np.nan)
            return x

        import jax
        logger.warning("FaultPlan: NaN-poisoning the batch for this step")
        return jax.tree_util.tree_map(leaf, batch)

    def should_hang(self, step_attempt: int) -> bool:
        return (self.hang_at_step is not None
                and step_attempt == self.hang_at_step)

    def hang(self) -> None:
        """Stall in-step (the deadlocked-collective / stuck-host-callback
        shape). The guardrails watchdog is expected to kill the process
        long before ``hang_seconds`` elapses."""
        import time

        logger.warning("FaultPlan: injecting in-step hang (%.0fs) — the "
                       "watchdog should trip first", self.hang_seconds)
        time.sleep(self.hang_seconds)

    def preempt(self, global_step: int) -> None:
        """Deliver the injected preemption: SIGTERM to self, default
        disposition (process death), like a real maintenance event."""
        logger.warning("FaultPlan: injecting preemption (SIGTERM) after "
                       "global step %d", global_step)
        signal.signal(signal.SIGTERM, signal.SIG_DFL)
        os.kill(os.getpid(), signal.SIGTERM)

    # -- multi-slice chaos (live elasticity; resilience/elastic.py) -----
    def should_slice_preempt(self, step_attempt: int) -> bool:
        """Keyed on step ATTEMPTS like hang/nan: the elastic shrink does
        not rewind the counter, so the warning fires exactly once."""
        return (self.slice_preempt_at_step is not None
                and step_attempt == self.slice_preempt_at_step)

    def slice_preempt(self) -> None:
        """Deliver the preemption ADVANCE WARNING: SIGTERM to self with
        whatever handler is installed — the live-elasticity coordinator's,
        when elasticity.live is on. The real platform would hard-kill
        ``preempt_grace_seconds`` later; the deterministic injection
        leaves enforcement to the coordinator's own grace bookkeeping."""
        logger.warning(
            "FaultPlan: injecting slice-preemption advance warning "
            "(SIGTERM, grace %.1fs, victim slice %s)",
            self.preempt_grace_seconds,
            self.slice_preempt_slice
            if self.slice_preempt_slice is not None else "<last>")
        os.kill(os.getpid(), signal.SIGTERM)

    # -- serving chaos (serving/resilience.py; docs/SERVING.md) ---------
    def should_serve_decode_fault(self, dispatch_attempt: int) -> bool:
        """Raise on this decode DISPATCH attempt? Keyed on the engine's
        monotonic dispatch-attempt counter (retries advance it, steps
        never rewind), active for the window
        ``[at_step, at_step + count)`` — so the count dials the depth of
        the recovery path exercised (retry-only vs rebuild+replay)."""
        return (self.serve_decode_fault_at_step is not None
                and self.serve_decode_fault_at_step <= dispatch_attempt
                < self.serve_decode_fault_at_step
                + self.serve_decode_fault_count)

    def serve_decode_fault(self, dispatch_attempt: int) -> None:
        raise RuntimeError(
            f"FaultPlan: injected serving decode-dispatch fault "
            f"(dispatch attempt {dispatch_attempt})")

    def should_serve_slow_step(self, dispatch_attempt: int) -> bool:
        return (self.serve_slow_step_at_step is not None
                and self.serve_slow_step_at_step <= dispatch_attempt
                < self.serve_slow_step_at_step + self.serve_slow_step_count)

    def serve_slow_step(self) -> None:
        """Stall inside the decode timing window (the straggler-step
        shape): the slow-step anomaly detector and the wall-clock
        timeout are expected to see it."""
        import time

        logger.warning("FaultPlan: injecting slow serving step (%.3fs)",
                       self.serve_slow_step_seconds)
        time.sleep(self.serve_slow_step_seconds)

    def should_serve_storm(self, serve_step: int) -> bool:
        """Fire the request-storm burst at this serving step boundary?
        Keyed on the engine step counter (serving steps never rewind),
        exact match — the burst fires once."""
        return (self.serve_storm_at_step is not None
                and serve_step == self.serve_storm_at_step)

    def should_rejoin(self, step_attempt: int,
                      shrink_step_attempt: Optional[int]) -> bool:
        """The preempted slice returns ``rejoin_after_steps`` step
        attempts after the shrink the warning caused."""
        return (self.rejoin_after_steps is not None
                and shrink_step_attempt is not None
                and step_attempt >= shrink_step_attempt
                + self.rejoin_after_steps)


def corrupt_one_shard(ckpt_path: str, manifest: Dict[str, Any]) -> str:
    """Flip bytes in the first (name-sorted) shard of a committed
    checkpoint — the deterministic torn-write fault. Returns the file."""
    name = sorted(manifest["shards"])[0]
    fname = os.path.join(ckpt_path, manifest["shards"][name]["file"])
    with open(fname, "r+b") as f:
        f.seek(0, os.SEEK_END)
        size = f.tell()
        # Hit the payload, not just the npy header: flip a run of bytes in
        # the back half of the file.
        pos = max(size // 2, min(size - 1, 128))
        f.seek(pos)
        chunk = f.read(min(64, size - pos))
        f.seek(pos)
        f.write(bytes(b ^ 0xFF for b in chunk))
    logger.warning("FaultPlan: corrupted shard %r in %s", name, fname)
    return fname
