"""Elastic auto-resume supervisor.

The launcher-level recovery loop: run the training command; when it dies
(real preemption, injected SIGTERM, OOM-kill, I/O crash), restart it up to
``max_restarts`` times with exponential backoff. Each incarnation sees
``DSTPU_RESUME_ATTEMPT`` in its environment; the training side
(:func:`deepspeed_tpu.resilience.restore`) resumes from the newest complete
manifest, and :class:`~.fault.FaultPlan` uses the same variable to keep
injected faults from re-firing after the restart they were meant to cause.

On restart the supervisor can also re-solve the elastic world size: given
the job's ds-config and the chip count still available,
:func:`deepspeed_tpu.elasticity.pick_preferred_world` selects the largest
valid world — the restarted command reads ``DSTPU_ELASTIC_WORLD`` and
builds its mesh/config for that world, and the resharded-load in
``restore()`` re-partitions ZeRO state accordingly.
"""

import os
import subprocess
import sys
import time
from typing import Callable, Dict, Iterable, List, Optional

from deepspeed_tpu.config.constants import (
    ELASTIC_PREEMPT_EXIT_CODE_DEFAULT,
    GUARDRAILS_WATCHDOG_EXIT_CODE_DEFAULT, MEMORY_OOM_EXIT_CODE_DEFAULT)
from deepspeed_tpu.guardrails.retry import backoff_delay
from deepspeed_tpu.resilience.fault import RESUME_ATTEMPT_ENV
from deepspeed_tpu.utils.logging import logger

ELASTIC_WORLD_ENV = "DSTPU_ELASTIC_WORLD"
# Cap on the exponential restart delay: a crash-looping job's delay grew
# without bound before (backoff * 2**(restarts-1)); past ~a minute more
# waiting buys nothing — either the fault is transient (the cap is plenty)
# or it is permanent (the restart budget ends the loop).
MAX_RESTART_BACKOFF_DEFAULT = 60.0


class Supervisor:
    """Restart-on-death driver for one training command.

    Restart delays follow the shared capped + jittered exponential schedule
    (guardrails/retry.py). Exit codes listed in ``immediate_restart_rcs``
    (by default the guardrails watchdog's distinct rc) restart with NO
    delay: a watchdog kill means the job already sat through a full step
    deadline doing nothing — backing off on top would double the waste.
    Exit codes in ``oom_rcs`` (by default the memory observatory's
    distinct OOM rc, telemetry/memory.py) are NOT restarted at all: a
    deterministic RESOURCE_EXHAUSTED is a config bug — the same model on
    the same devices re-OOMs on every attempt, so a restart loop just
    burns the budget re-compiling into the same wall. The attempt's run
    manifest is stamped ``cause=oom`` and the loop ends with that rc.
    """

    def __init__(self,
                 cmd: List[str],
                 max_restarts: int = 3,
                 env: Optional[Dict[str, str]] = None,
                 backoff: float = 0.5,
                 max_backoff: float = MAX_RESTART_BACKOFF_DEFAULT,
                 jitter: float = 0.25,
                 immediate_restart_rcs: Optional[Iterable[int]] = None,
                 oom_rcs: Optional[Iterable[int]] = None,
                 warned_rcs: Optional[Iterable[int]] = None,
                 ckpt_dir: Optional[str] = None,
                 run_dir: Optional[str] = None,
                 available_worlds: Optional[Callable[[int], int]] = None):
        if max_restarts < 0:
            raise ValueError("max_restarts must be >= 0")
        self.cmd = list(cmd)
        self.max_restarts = int(max_restarts)
        self.env = dict(env or {})
        self.backoff = float(backoff)
        self.max_backoff = float(max_backoff)
        self.jitter = float(jitter)
        self.immediate_restart_rcs = set(
            immediate_restart_rcs if immediate_restart_rcs is not None
            else (GUARDRAILS_WATCHDOG_EXIT_CODE_DEFAULT,))
        self.oom_rcs = set(oom_rcs if oom_rcs is not None
                           else (MEMORY_OOM_EXIT_CODE_DEFAULT,))
        # The live-elasticity coordinator's distinct rc (resilience/
        # elastic.py): the advance warning WAS handled (state drained)
        # but no surviving capacity fit a valid world. Classified
        # `preemption_warned` — restarted like any preemption, but the
        # manifests record that elasticity did its half of the job.
        self.warned_rcs = set(warned_rcs if warned_rcs is not None
                              else (ELASTIC_PREEMPT_EXIT_CODE_DEFAULT,))
        self.ckpt_dir = ckpt_dir
        # Goodput run dir (the child's telemetry.dir): when set, each
        # attempt's run manifest gets its exit rc / restart cause stamped
        # post-mortem — the child rarely gets to write those itself
        # (telemetry/goodput.py; tools/goodput_report.py merges them).
        self.run_dir = run_dir
        self.available_worlds = available_worlds
        self.restarts = 0
        self.immediate_restarts = 0
        self.oom_exits = 0
        self.exit_codes: List[int] = []
        # Hosts the fleet layer marked as persistent stragglers (read from
        # the run dir's fleet breakdown after each attempt) — surfaced in
        # the logs today, and the input the elasticity policy (ROADMAP
        # item 4) will use to pick which slice to drop on reshard.
        self.straggler_hosts: List[str] = []
        # Goodput-costed eviction decisions stamped into the run
        # manifests after each attempt (resilience/elastic.py cost
        # model; rendered by tools/fleet_report.py).
        self.eviction_decisions: List[Dict] = []
        self.metrics = None
        if ckpt_dir:
            from deepspeed_tpu.resilience.checkpoint import METRICS_FILE
            from deepspeed_tpu.utils.monitor import MetricsJSONL
            os.makedirs(ckpt_dir, exist_ok=True)
            self.metrics = MetricsJSONL(os.path.join(ckpt_dir, METRICS_FILE))

    def _child_env(self, attempt: int) -> Dict[str, str]:
        from deepspeed_tpu.telemetry.goodput import ATTEMPT_START_WALL_ENV
        env = {**os.environ, **self.env,
               RESUME_ATTEMPT_ENV: str(attempt),
               # Spawn wall time: the child's goodput accountant backdates
               # the attempt to it, so interpreter start-up (imports) is
               # attributed to init_restore instead of vanishing.
               ATTEMPT_START_WALL_ENV: repr(time.time())}
        if self.available_worlds is not None:
            env[ELASTIC_WORLD_ENV] = str(self.available_worlds(attempt))
        return env

    def _finalize_attempt(self, attempt: int, rc: int,
                          start_wall: float) -> None:
        """Stamp the attempt's run manifest(s) with its fate (goodput
        cross-attempt reporting). Best-effort: accounting must never take
        down the recovery loop."""
        if not self.run_dir:
            return
        from deepspeed_tpu.telemetry.goodput import (classify_exit,
                                                     finalize_attempt_manifests)
        try:
            finalize_attempt_manifests(
                self.run_dir, attempt, rc,
                classify_exit(rc, self.immediate_restart_rcs, self.oom_rcs,
                              self.warned_rcs),
                start_wall, time.time())
        except Exception as e:  # noqa: BLE001
            logger.warning("supervisor: manifest finalize failed: %s", e)

    def _note_stragglers(self, attempt: int = 0) -> None:
        """Surface persistent-straggler verdicts from the fleet breakdown
        file alongside the restart decision, and stamp a goodput-costed
        eviction decision (host, z-score, projected gain vs. restart
        cost) into the attempt's run manifests for tools/fleet_report.py.
        Best-effort."""
        if not self.run_dir:
            return
        try:
            from deepspeed_tpu.telemetry.fleet import read_straggler_evidence
            evidence = read_straggler_evidence(self.run_dir)
        except Exception:  # noqa: BLE001
            return
        hosts = sorted(h for h, e in evidence.items() if e["persistent"])
        if not hosts:
            return
        self.straggler_hosts = hosts
        logger.warning(
            "supervisor: fleet telemetry marked persistent straggler "
            "host(s) %s — throughput is paced by them; an elastic "
            "restart excluding them may recover goodput", hosts)
        # Supervisor-level cost model: the alternative to keeping the
        # straggler is a RESTART at a smaller world, so the cost side is
        # the attempt's measured in-process reshard time when one
        # happened, else the cold-restart proxy (the live-elasticity
        # default). The gain side is the fleet-measured cumulative
        # straggler_sec — time already lost, projected to repeat.
        try:
            from deepspeed_tpu.config.config import LiveEvictionConfig
            from deepspeed_tpu.resilience.elastic import evaluate_eviction
            from deepspeed_tpu.telemetry.goodput import \
                stamp_eviction_decisions
            defaults = LiveEvictionConfig()
            decisions = []
            for host in hosts:
                e = evidence[host]
                decision = evaluate_eviction(
                    # The breakdown's windowed per-step excess — SAME
                    # units the in-process coordinator feeds the model
                    # (lost_sec is cumulative over flushed steps, not a
                    # rate).
                    e["lost_sec_per_step"],
                    defaults.horizon_steps,
                    defaults.assumed_reshard_sec,
                    defaults.min_gain_factor)
                decision.update(host=host, zscore=e.get("last_zscore"),
                                step=None, source="supervisor",
                                lost_sec_total=e["lost_sec"])
                decisions.append(decision)
                logger.warning(
                    "supervisor: eviction decision for %s: %s (projected "
                    "gain %.1fs vs %.1fx restart cost %.1fs)", host,
                    "EVICT" if decision["evict"] else "keep",
                    decision["projected_gain_sec"],
                    decision["min_gain_factor"],
                    decision["reshard_cost_sec"])
            self.eviction_decisions = decisions
            stamp_eviction_decisions(self.run_dir, attempt, decisions)
        except Exception as e:  # noqa: BLE001 — accounting must never
            # take down the recovery loop
            logger.warning("supervisor: eviction stamping failed: %s", e)

    def run(self) -> int:
        """Run until clean exit or restart budget exhausted; returns the
        final exit code (0 on success)."""
        attempt = 0
        while True:
            logger.info("supervisor: launching attempt %d: %s", attempt,
                        " ".join(self.cmd))
            start_wall = time.time()
            proc = subprocess.Popen(self.cmd, env=self._child_env(attempt))
            try:
                rc = proc.wait()
            except KeyboardInterrupt:
                proc.terminate()
                try:
                    proc.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    proc.kill()
                raise
            self.exit_codes.append(rc)
            self._finalize_attempt(attempt, rc, start_wall)
            self._note_stragglers(attempt)
            if rc == 0:
                if self.metrics is not None:
                    self.metrics.add_scalar(
                        "Train/Resilience/recovery_count", self.restarts,
                        attempt)
                return 0
            if rc in self.oom_rcs:
                # A deterministic OOM is a CONFIG bug, not a preemption:
                # the same state on the same devices re-OOMs every
                # attempt, so restarting (hot or backed-off) only burns
                # the budget. The crashdump + what-if table say what to
                # change; stop here with the distinct rc.
                self.oom_exits += 1
                logger.error(
                    "supervisor: attempt %d exited rc=%d (cause=oom) — "
                    "NOT restarting: a deterministic OOM re-fires every "
                    "attempt. Inspect the memory crashdump (oom_step*/ "
                    "under the crashdump dir) and the memory_plan.json "
                    "what-if table (tools/memory_report.py) for a "
                    "fitting ZeRO stage / offload / microbatch", attempt,
                    rc)
                if self.metrics is not None:
                    self.metrics.add_scalar(
                        "Train/Resilience/worker_exit_code", rc, attempt)
                return rc
            if self.restarts >= self.max_restarts:
                logger.error(
                    "supervisor: attempt %d exited rc=%d and the restart "
                    "budget (%d) is exhausted — giving up", attempt, rc,
                    self.max_restarts)
                return rc
            self.restarts += 1
            attempt += 1
            if rc in self.immediate_restart_rcs:
                # Watchdog-style death: the hang already consumed a full
                # step deadline — restart NOW.
                self.immediate_restarts += 1
                delay = 0.0
            else:
                delay = backoff_delay(self.restarts - 1, self.backoff,
                                      max_delay=self.max_backoff,
                                      jitter=self.jitter)
            logger.warning(
                "supervisor: worker died rc=%d — restart %d/%d in %.2fs%s",
                rc, self.restarts, self.max_restarts, delay,
                " (immediate: watchdog rc)" if delay == 0.0 else "")
            if self.metrics is not None:
                self.metrics.add_scalar("Train/Resilience/worker_exit_code",
                                        rc, attempt)
            if delay > 0.0:
                time.sleep(delay)


def supervise_main(argv: Optional[List[str]] = None) -> int:
    """``python -m deepspeed_tpu.resilience.supervisor [opts] -- cmd...`` —
    standalone auto-resume wrapper for a single-host training command."""
    import argparse

    ap = argparse.ArgumentParser(
        description="Auto-resume supervisor: restart a training command on "
                    "failure")
    ap.add_argument("--max_restarts", type=int, default=3)
    ap.add_argument("--backoff", type=float, default=0.5)
    ap.add_argument("--max_backoff", type=float,
                    default=MAX_RESTART_BACKOFF_DEFAULT,
                    help="Cap on the exponential restart delay (seconds)")
    ap.add_argument("--immediate_rc", type=int, action="append",
                    default=None,
                    help="Exit code restarted with NO backoff (repeatable);"
                         " default: the guardrails watchdog rc 113. Set "
                         "when the ds-config overrides "
                         "guardrails.watchdog.exit_code")
    ap.add_argument("--oom_rc", type=int, action="append", default=None,
                    help="Exit code classified cause=oom and NOT restarted "
                         "(repeatable); default: the memory observatory rc "
                         "114. Set when the ds-config overrides "
                         "telemetry.memory.oom_exit_code")
    ap.add_argument("--warned_rc", type=int, action="append", default=None,
                    help="Exit code classified cause=preemption_warned "
                         "(live elasticity caught the grace-window SIGTERM "
                         "but no capacity survived; restarted normally). "
                         "Default: rc 115. Set when the ds-config overrides "
                         "elasticity.live.exit_code")
    ap.add_argument("--checkpoint_dir", type=str, default=None)
    ap.add_argument("--run_dir", type=str, default=None,
                    help="Goodput run dir (the child's telemetry.dir): "
                         "attempt run manifests there get exit rc / "
                         "restart cause stamped for goodput_report")
    ap.add_argument("cmd", nargs=argparse.REMAINDER,
                    help="training command (prefix with --)")
    args = ap.parse_args(argv)
    cmd = args.cmd[1:] if args.cmd[:1] == ["--"] else args.cmd
    if not cmd:
        ap.error("no command given")
    return Supervisor(cmd, max_restarts=args.max_restarts,
                      backoff=args.backoff, max_backoff=args.max_backoff,
                      immediate_restart_rcs=args.immediate_rc,
                      oom_rcs=args.oom_rc,
                      warned_rcs=args.warned_rc,
                      ckpt_dir=args.checkpoint_dir,
                      run_dir=args.run_dir).run()


if __name__ == "__main__":
    sys.exit(supervise_main())
