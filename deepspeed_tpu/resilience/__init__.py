"""Resilience subsystem: preemption-aware async checkpointing, deterministic
fault injection, and elastic auto-resume (see docs/RESILIENCE.md)."""

from deepspeed_tpu.resilience.checkpoint import (AsyncCheckpointManager,
                                                 ResilienceError,
                                                 find_restorable,
                                                 install_state_arrays,
                                                 list_checkpoints, restore,
                                                 snapshot_engine)
from deepspeed_tpu.resilience.elastic import (ELASTIC_METRIC_TAGS,
                                              PREEMPT_SLICE_ENV,
                                              ElasticCoordinator,
                                              LiveElasticityError,
                                              build_elastic,
                                              clear_rejoin_request,
                                              evaluate_eviction,
                                              read_rejoin_request,
                                              request_rejoin)
from deepspeed_tpu.resilience.fault import (FAULT_PLAN_ENV,
                                            RESUME_ATTEMPT_ENV, FaultPlan,
                                            corrupt_one_shard)
from deepspeed_tpu.resilience.supervisor import (ELASTIC_WORLD_ENV,
                                                 Supervisor, supervise_main)

__all__ = [
    "AsyncCheckpointManager", "ResilienceError", "find_restorable",
    "install_state_arrays", "list_checkpoints", "restore", "snapshot_engine",
    "FaultPlan", "corrupt_one_shard", "FAULT_PLAN_ENV", "RESUME_ATTEMPT_ENV",
    "Supervisor", "supervise_main", "ELASTIC_WORLD_ENV",
    "ELASTIC_METRIC_TAGS", "PREEMPT_SLICE_ENV", "ElasticCoordinator",
    "LiveElasticityError", "build_elastic", "clear_rejoin_request",
    "evaluate_eviction", "read_rejoin_request", "request_rejoin",
]
