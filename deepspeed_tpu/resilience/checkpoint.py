"""Preemption-aware asynchronous checkpointing.

The engine's orbax path (``runtime/checkpointing.py``) is synchronous and
best-effort: a save blocks the step loop for the full serialize+write, and a
death mid-write can leave a directory that *looks* like a checkpoint. This
module is the production recovery tier:

- **off the step path** — ``save()`` only snapshots device state to host
  (async D2H started leaf-by-leaf, then gathered) and enqueues; a background
  writer thread does the serialization and disk I/O;
- **double-buffered** — at most one snapshot is in flight and one pending;
  enqueueing while a write runs *replaces* the pending snapshot (latest
  wins), so a slow disk back-pressures to "skip intermediate checkpoints",
  never "stall training";
- **atomic commit** — shards + manifest are written into a ``.tmp-`` dir
  which is ``os.replace``d into place; a directory named ``step_*`` with a
  parseable manifest therefore IS a complete checkpoint, and a death
  mid-write leaves only a tmp dir the loader never considers;
- **verified** — the manifest records a sha256 per shard (plus shape/dtype
  and the elastic-config hash); the loader re-hashes on restore and falls
  back to the previous complete checkpoint on any mismatch (torn shard,
  bitrot, truncation);
- **retried** — transient write failures retry with exponential backoff
  (``max_retries``/``backoff``), with :class:`~.fault.FaultPlan` able to
  inject the failures deterministically;
- **garbage-collected** — keep-last-N, applied after every commit.

Restore (:func:`restore`) places each saved leaf onto the *restoring*
engine's shardings. Shards store the full (gathered) arrays, so an elastic
restart at a different world size reshards ZeRO state by construction — the
device_put against the new engine's NamedShardings is the reshard the
cross-replica-sharding paper's weight-update partitioning needs on recovery.
"""

import contextlib
import hashlib
import json
import os
import pickle
import re
import shutil
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from deepspeed_tpu.guardrails.retry import backoff_delay
from deepspeed_tpu.resilience.fault import (RESUME_ATTEMPT_ENV, FaultPlan,
                                            corrupt_one_shard)
from deepspeed_tpu.utils.logging import logger

MANIFEST_FILE = "manifest.json"
CLIENT_STATE_FILE = "client_state.pkl"
METRICS_FILE = "resilience_metrics.jsonl"
MANIFEST_FORMAT = 1
_STEP_DIR_RE = re.compile(r"^step_(\d+)$")
_TMP_PREFIX = ".tmp-"


class ResilienceError(RuntimeError):
    pass


def _leaf_name(path) -> str:
    parts = []
    for k in path:
        if hasattr(k, "name"):       # GetAttrKey (NamedTuple fields)
            parts.append(str(k.name))
        elif hasattr(k, "key"):      # DictKey
            parts.append(str(k.key))
        elif hasattr(k, "idx"):      # SequenceKey
            parts.append(str(k.idx))
        else:
            parts.append(str(k))
    return ".".join(parts)


def _flatten_named(tree) -> Tuple[List[Tuple[str, Any]], Any]:
    """[(dotted-name, leaf)], treedef — names are stable for a fixed
    TrainState structure, which save and restore both derive from the
    engine, so matching by name is exact."""
    import jax

    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    return [(_leaf_name(p), leaf) for p, leaf in flat], treedef


def _storable(arr: np.ndarray) -> Tuple[np.ndarray, str]:
    """(numpy-native array, original dtype string). bfloat16 (and other
    ml_dtypes floats numpy can't round-trip by name) are stored widened to
    fp32; restore casts back to the template leaf's dtype — lossless.
    (No ascontiguousarray: it promotes 0-d scalars to 1-d, and ``tobytes``
    emits C order regardless.)"""
    arr = np.asarray(arr)
    orig = str(arr.dtype)
    try:
        np.dtype(orig)
    except TypeError:
        arr = arr.astype(np.float32)
    return arr, orig


class _Snapshot:
    """Host-side copy of everything a resume needs, ready to serialize."""

    def __init__(self, step: int, arrays: List[Tuple[str, np.ndarray]],
                 meta: Dict[str, Any], client_state: Dict[str, Any]):
        self.step = step
        self.arrays = arrays
        self.meta = meta
        self.client_state = client_state


def snapshot_engine(engine, client_state: Optional[Dict] = None) -> _Snapshot:
    """Copy engine state to host. Starts every leaf's D2H copy before
    gathering any (overlapped transfers), so the step-path cost is one
    device sync + the copies — no disk I/O."""
    import jax

    state = engine._snapshot_state()
    named, _ = _flatten_named(state)
    for _, leaf in named:
        if hasattr(leaf, "copy_to_host_async"):
            leaf.copy_to_host_async()
    arrays = [(name, np.asarray(jax.device_get(leaf)))
              for name, leaf in named]
    meta = {
        "format": MANIFEST_FORMAT,
        "step": int(engine.global_steps),
        "micro_steps": int(engine.micro_steps),
        "elastic_hash": getattr(engine, "elastic_hash", ""),
        # Live-elasticity world-change epoch (resilience/elastic.py):
        # which incarnation of the mesh wrote this checkpoint — 0 until a
        # world change happens. Informational (restore reshards onto
        # whatever mesh the restoring engine runs), but post-mortem tools
        # can line checkpoints up against the manifest's world timeline.
        "elastic_epoch": int(getattr(engine, "elastic_epoch", 0)),
        "world_size": int(engine.mesh.size),
        "dp_world_size": int(engine.dp_size),
        "zero_stage": int(engine.config.zero_config.stage),
        "ds_version": _version(),
        "lr_scheduler": (engine.lr_scheduler.state_dict()
                         if engine.lr_scheduler is not None else None),
    }
    return _Snapshot(meta["step"], arrays, meta, client_state or {})


def _version() -> str:
    from deepspeed_tpu.version import __version__

    return __version__


class AsyncCheckpointManager:
    """Background double-buffered checkpoint writer. One per engine."""

    def __init__(self,
                 ckpt_dir: str,
                 interval: int = 1,
                 keep_last: int = 3,
                 max_retries: int = 3,
                 backoff: float = 0.05,
                 async_write: bool = True,
                 fault_plan: Optional[FaultPlan] = None,
                 monitor=None,
                 telemetry=None,
                 goodput=None):
        if interval < 1:
            raise ValueError("checkpoint interval must be >= 1")
        if keep_last < 1:
            raise ValueError("keep_last must be >= 1")
        self.ckpt_dir = ckpt_dir
        self.interval = int(interval)
        self.keep_last = int(keep_last)
        self.max_retries = int(max_retries)
        self.backoff = float(backoff)
        self.async_write = bool(async_write)
        self.fault_plan = fault_plan
        self.monitor = monitor
        self.telemetry = telemetry
        self.goodput = goodput
        self.stats = {"saved": 0, "dropped": 0, "retries": 0, "failed": 0}
        self.last_error: Optional[BaseException] = None
        os.makedirs(ckpt_dir, exist_ok=True)
        from deepspeed_tpu.utils.monitor import MetricsJSONL
        self.metrics = MetricsJSONL(os.path.join(ckpt_dir, METRICS_FILE))

        self._cv = threading.Condition()
        self._pending: Optional[_Snapshot] = None
        self._writing = False
        self._closed = False
        # Test hook: clear to hold the writer before it takes a snapshot
        # (makes the latest-wins double-buffer observable deterministically).
        self._unpaused = threading.Event()
        self._unpaused.set()
        self._thread = None
        if self.async_write:
            self._thread = threading.Thread(
                target=self._writer_loop, name="ckpt-writer", daemon=True)
            self._thread.start()
            # The writer is a daemon thread (it must never block a SIGTERM
            # teardown), so a CLEAN interpreter exit would otherwise kill
            # it mid-write and silently lose the newest auto-saved
            # checkpoint. Drain pending work at exit; bounded because
            # retries are bounded (max_retries × backoff).
            import atexit
            atexit.register(self._drain_at_exit)

    # ------------------------------------------------------------------
    def save(self, engine, client_state: Optional[Dict] = None,
             blocking: bool = False) -> None:
        """Snapshot now; write in the background (or inline when
        ``async_write=False``). Never raises for write errors — the writer
        retries, and terminal failures land in ``stats['failed']`` /
        ``last_error`` plus the log (checkpointing must not kill the run
        it exists to protect)."""
        t0 = time.monotonic()
        # Goodput attribution (telemetry/goodput.py): save() runs on the
        # step path, so the D2H snapshot is step-path time; for a
        # sync-write manager the inline write below stalls the step too.
        gp = self.goodput
        with (gp.measure("ckpt_snapshot") if gp is not None
              else contextlib.nullcontext()):
            with self._span("ckpt_snapshot", step=int(engine.global_steps)):
                snap = snapshot_engine(engine, client_state=client_state)
        snap.meta["snapshot_sec"] = round(time.monotonic() - t0, 6)
        if not self.async_write:
            with (gp.measure("ckpt_write_stall") if gp is not None
                  else contextlib.nullcontext()):
                self._write_with_retries(snap)
            return
        with self._cv:
            if self._closed:
                raise ResilienceError("AsyncCheckpointManager is closed")
            if self._pending is not None:
                # Double buffer: one writing + one pending; latest wins.
                self.stats["dropped"] += 1
                self._counter("ckpt/dropped", step=snap.step)
                logger.warning(
                    "async checkpoint backlog: dropping pending step %d "
                    "snapshot in favour of step %d", self._pending.step,
                    snap.step)
            self._pending = snap
            self._cv.notify_all()
        if blocking:
            self.wait()

    def wait(self) -> None:
        """Drain: returns once no snapshot is pending or being written.
        The caller genuinely blocks on checkpoint I/O here, so the wait is
        goodput-attributed as ckpt_write_stall."""
        with (self.goodput.measure("ckpt_write_stall")
              if self.goodput is not None else contextlib.nullcontext()):
            with self._cv:
                self._cv.wait_for(
                    lambda: self._pending is None and not self._writing)

    def _drain_at_exit(self) -> None:
        try:
            self.close()
        except Exception:  # noqa: BLE001 — never raise during teardown
            pass

    def close(self) -> None:
        if self._thread is not None:
            self.wait()
            with self._cv:
                self._closed = True
                self._cv.notify_all()
            self._thread.join(timeout=30)
            self._thread = None
            import atexit
            try:
                atexit.unregister(self._drain_at_exit)
            except Exception:  # noqa: BLE001
                pass
        # Sync-write managers have no thread but still own the metrics
        # handle — close it regardless so the final line is flushed.
        self.metrics.close()

    # ------------------------------------------------------------------
    def _writer_loop(self) -> None:
        while True:
            self._unpaused.wait()
            with self._cv:
                self._cv.wait_for(
                    lambda: self._pending is not None or self._closed)
                if self._pending is None and self._closed:
                    return
                snap, self._pending = self._pending, None
                self._writing = True
                self._cv.notify_all()
            try:
                self._write_with_retries(snap)
            finally:
                with self._cv:
                    self._writing = False
                    self._cv.notify_all()

    def _span(self, name: str, **args):
        """Tracer span when a telemetry facade was handed in (no-op
        otherwise) — ckpt_snapshot/ckpt_write show up in the step trace."""
        if self.telemetry is not None:
            return self.telemetry.span(name, **args)
        import contextlib
        return contextlib.nullcontext()

    def _counter(self, name: str, step: int) -> None:
        if self.telemetry is not None:
            self.telemetry.registry.counter(name).inc(step=step)

    def _write_with_retries(self, snap: _Snapshot) -> None:
        t0 = time.monotonic()
        with self._span("ckpt_write", step=snap.step):
            for attempt in range(self.max_retries + 1):
                try:
                    path = self._write_once(snap)
                    break
                except Exception as e:  # noqa: BLE001 — retry any write fault
                    self.last_error = e
                    if attempt >= self.max_retries:
                        self.stats["failed"] += 1
                        self._counter("ckpt/failed", step=snap.step)
                        logger.error(
                            "checkpoint step %d failed after %d attempts: %s",
                            snap.step, attempt + 1, e)
                        return
                    self.stats["retries"] += 1
                    self._counter("ckpt/retries", step=snap.step)
                    # Shared jittered-exponential schedule (guardrails/
                    # retry.py): capped so a long outage never produces an
                    # hour-long sleep, jittered so a pod's workers don't
                    # hammer the recovered filesystem in lockstep.
                    delay = backoff_delay(attempt, self.backoff,
                                          max_delay=60.0, jitter=0.25)
                    logger.warning(
                        "checkpoint step %d write attempt %d failed (%s); "
                        "retrying in %.3fs", snap.step, attempt + 1, e, delay)
                    time.sleep(delay)
        latency = time.monotonic() - t0
        self.stats["saved"] += 1
        # The JSONL-beside-the-checkpoints file keeps its contract (the
        # auto-resume probe and supervisor read it); the registry fans the
        # same scalars out to every configured telemetry sink.
        self.metrics.add_scalar("Train/Checkpoint/write_latency_sec",
                                latency, snap.step)
        self.metrics.add_scalar("Train/Checkpoint/snapshot_sec",
                                snap.meta.get("snapshot_sec", 0.0), snap.step)
        if self.telemetry is not None:
            reg = self.telemetry.registry
            reg.gauge("ckpt/write_latency_sec").set(latency, step=snap.step)
            reg.gauge("ckpt/snapshot_sec").set(
                snap.meta.get("snapshot_sec", 0.0), step=snap.step)
            self._counter("ckpt/saved", step=snap.step)
        elif self.monitor is not None:
            # No facade (standalone manager construction): legacy direct
            # monitor emission.
            self.monitor.add_scalar("Train/Checkpoint/write_latency_sec",
                                    latency, snap.step)
        logger.info("checkpoint step %d committed to %s (%.3fs)",
                    snap.step, path, latency)
        if (self.fault_plan is not None
                and self.fault_plan.should_corrupt(snap.step)):
            manifest = _read_manifest(path)
            corrupt_one_shard(path, manifest)
        self._gc()

    def _write_once(self, snap: _Snapshot) -> str:
        final = os.path.join(self.ckpt_dir, f"step_{snap.step:08d}")
        tmp = os.path.join(self.ckpt_dir, f"{_TMP_PREFIX}step_{snap.step:08d}")
        if os.path.isdir(tmp):
            shutil.rmtree(tmp)  # leftover from a failed earlier attempt
        os.makedirs(tmp)
        shards: Dict[str, Dict[str, Any]] = {}
        for i, (name, arr) in enumerate(snap.arrays):
            stored, orig_dtype = _storable(arr)
            fname = f"shard_{i:05d}.bin"
            data = stored.tobytes()
            if (self.fault_plan is not None
                    and self.fault_plan.take_io_error()):
                raise OSError(f"injected checkpoint I/O error ({fname})")
            with open(os.path.join(tmp, fname), "wb") as f:
                f.write(data)
                f.flush()
                os.fsync(f.fileno())
            shards[name] = {
                "file": fname,
                "sha256": hashlib.sha256(data).hexdigest(),
                "shape": list(stored.shape),
                "stored_dtype": str(stored.dtype),
                "dtype": orig_dtype,
            }
        cs_blob = pickle.dumps(snap.client_state)
        with open(os.path.join(tmp, CLIENT_STATE_FILE), "wb") as f:
            f.write(cs_blob)
            f.flush()
            os.fsync(f.fileno())
        manifest = dict(snap.meta)
        manifest["shards"] = shards
        manifest["client_state_sha256"] = hashlib.sha256(cs_blob).hexdigest()
        with open(os.path.join(tmp, MANIFEST_FILE), "w") as f:
            json.dump(manifest, f, indent=1)
            f.flush()
            os.fsync(f.fileno())
        if os.path.isdir(final):
            shutil.rmtree(final)  # re-save of the same step supersedes
        os.replace(tmp, final)    # the atomic commit
        return final

    def _gc(self) -> None:
        entries = list_checkpoints(self.ckpt_dir)
        for _, path in entries[:-self.keep_last]:
            shutil.rmtree(path, ignore_errors=True)
            logger.info("checkpoint GC: removed %s", path)


# ---------------------------------------------------------------------------
# Load / resume side
# ---------------------------------------------------------------------------

def list_checkpoints(ckpt_dir: str) -> List[Tuple[int, str]]:
    """[(step, path)] of committed checkpoints, oldest first. Tmp dirs from
    a death mid-write never match (the rename-commit contract)."""
    out = []
    if not os.path.isdir(ckpt_dir):
        return out
    for entry in os.listdir(ckpt_dir):
        m = _STEP_DIR_RE.match(entry)
        if m:
            out.append((int(m.group(1)), os.path.join(ckpt_dir, entry)))
    return sorted(out)


def _read_manifest(path: str) -> Dict[str, Any]:
    with open(os.path.join(path, MANIFEST_FILE)) as f:
        return json.load(f)


def _load_verified(path: str):
    """Read + digest-verify every shard of one checkpoint. Raises on any
    mismatch/corruption — the caller falls back to an older checkpoint."""
    manifest = _read_manifest(path)
    if manifest.get("format") != MANIFEST_FORMAT:
        raise ResilienceError(
            f"unsupported manifest format {manifest.get('format')}")
    arrays: Dict[str, np.ndarray] = {}
    for name, rec in manifest["shards"].items():
        fname = os.path.join(path, rec["file"])
        with open(fname, "rb") as f:
            data = f.read()
        digest = hashlib.sha256(data).hexdigest()
        if digest != rec["sha256"]:
            raise ResilienceError(
                f"shard {name!r} digest mismatch in {path} "
                f"({digest[:12]} != {rec['sha256'][:12]}): torn or corrupt")
        arr = np.frombuffer(data, dtype=np.dtype(rec["stored_dtype"]))
        arrays[name] = arr.reshape(rec["shape"])
    cs_path = os.path.join(path, CLIENT_STATE_FILE)
    client_state: Dict[str, Any] = {}
    if os.path.exists(cs_path):
        with open(cs_path, "rb") as f:
            blob = f.read()
        if (hashlib.sha256(blob).hexdigest()
                != manifest.get("client_state_sha256")):
            raise ResilienceError(f"client_state digest mismatch in {path}")
        client_state = pickle.loads(blob)
    return manifest, arrays, client_state


def find_restorable(ckpt_dir: str):
    """Newest *complete, digest-verified* checkpoint, falling back past any
    corrupt/torn ones. Returns (path, manifest, arrays, client_state) or
    None when nothing usable exists."""
    for step, path in reversed(list_checkpoints(ckpt_dir)):
        try:
            manifest, arrays, client_state = _load_verified(path)
            return path, manifest, arrays, client_state
        except Exception as e:  # noqa: BLE001 — any damage means fall back
            logger.warning("checkpoint %s unusable (%s); falling back to "
                           "previous", path, e)
    return None


def install_state_arrays(engine, arrays: Dict[str, np.ndarray], *,
                         step: int, micro_steps: int,
                         lr_scheduler_state: Optional[Dict] = None) -> None:
    """Place named host arrays onto ``engine``'s current shardings and
    install them as the live TrainState (plus step counters and scheduler
    state). The shared epilogue of the on-disk :func:`restore` and the
    guardrails in-memory rollback (guardrails/rollback.py) — one
    implementation of "host arrays -> running engine"."""
    import jax

    template = engine._snapshot_state()
    named, treedef = _flatten_named(template)
    missing = [n for n, _ in named if n not in arrays]
    if missing:
        raise ResilienceError(
            f"snapshot lacks state leaves {missing[:5]} — was it written "
            "by a different model/optimizer configuration?")

    def place(name, leaf):
        arr = arrays[name]
        if tuple(arr.shape) != tuple(np.shape(leaf)):
            raise ResilienceError(
                f"leaf {name!r}: snapshot shape {arr.shape} != engine "
                f"shape {np.shape(leaf)}")
        arr = arr.astype(leaf.dtype)
        if hasattr(leaf, "sharding"):
            return jax.device_put(arr, leaf.sharding)
        return arr

    leaves = [place(name, leaf) for name, leaf in named]
    state = jax.tree_util.tree_unflatten(treedef, leaves)
    engine._apply_restored_state(state)
    engine.global_steps = int(step)
    engine.micro_steps = int(micro_steps)
    if engine.lr_scheduler is not None and lr_scheduler_state:
        engine.lr_scheduler.load_state_dict(lr_scheduler_state)


def restore(engine, ckpt_dir: str, monitor=None):
    """Auto-resume: load the newest complete checkpoint into ``engine``,
    resharding every leaf onto the engine's current placements (which may
    belong to a different elastic world size than the save).

    Returns ``(path, client_state)`` or ``(None, {})`` when there is
    nothing to resume from (fresh start)."""
    found = find_restorable(ckpt_dir)
    if found is None:
        logger.info("auto-resume: no usable checkpoint under %s — fresh "
                    "start", ckpt_dir)
        return None, {}
    path, manifest, arrays, client_state = found
    engine_hash = getattr(engine, "elastic_hash", "")
    saved_hash = manifest.get("elastic_hash", "")
    if engine_hash and saved_hash and engine_hash != saved_hash:
        raise ResilienceError(
            f"elastic config hash mismatch: checkpoint {path} was written "
            f"under {saved_hash[:12]} but this engine runs {engine_hash[:12]}"
            " — resuming would change the batch-size math mid-trajectory")

    try:
        install_state_arrays(engine, arrays, step=int(manifest["step"]),
                             micro_steps=int(manifest["micro_steps"]),
                             lr_scheduler_state=manifest.get("lr_scheduler"))
    except ResilienceError as e:
        raise ResilienceError(f"checkpoint {path}: {e}") from e

    if int(manifest.get("dp_world_size", engine.dp_size)) != engine.dp_size:
        logger.info(
            "auto-resume: elastic reshard dp %s -> %s (zero stage %s state "
            "re-partitioned onto the new mesh)", manifest.get("dp_world_size"),
            engine.dp_size, manifest.get("zero_stage"))

    attempt = int(os.environ.get(RESUME_ATTEMPT_ENV, "0") or 0)
    engine.recovery_count = attempt
    mon = monitor if monitor is not None else getattr(engine, "monitor", None)
    if mon is not None:
        mon.add_scalar("Train/Resilience/recovery_count", attempt,
                       engine.global_steps)
    if getattr(engine, "ckpt_manager", None) is not None:
        engine.ckpt_manager.metrics.add_scalar(
            "Train/Resilience/recovery_count", attempt, engine.global_steps)
    logger.warning("auto-resume: restored %s at global step %d (attempt %d)",
                   path, engine.global_steps, attempt)
    return path, client_state
