#!/usr/bin/env python
"""Render the device-time observatory's measured attribution as a report.

The measured companion of ``goodput_report``/``fleet_report``
(docs/OBSERVABILITY.md "Device-time observatory"): feed it the job's
``telemetry.dir`` — where each host's ``devicetime_breakdown.<host>.json``
lands (bare name on single-host runs) — and get, per host, the HLO
category table with roofline verdicts, host-dispatch gap, measured-vs-
modeled MFU and exposed-comm, and the top-K hottest-op table (the
Pallas-tier candidate list). ``--profile-dir`` instead parses raw
``jax.profiler`` captures (``**/*.trace.json.gz``) directly — the
hand-run-probe workflow, now one flag.

Parsing lives in the shared ``telemetry/traceparse.py`` (stdlib only,
loaded by file path) so this tool runs on hosts without jax, like the
other report tools.

Usage:
    python tools/devicetime_report.py RUN_DIR [--json]
    python tools/devicetime_report.py --profile-dir DIR [--top 10]
    python tools/devicetime_report.py --selftest
"""

import argparse
import glob
import gzip
import importlib.util
import json
import os
import sys
import tempfile
from typing import Any, Dict, List, Optional

BREAKDOWN_GLOB = "devicetime_breakdown*.json"


def _load_traceparse():
    cached = sys.modules.get("dstpu_traceparse")
    if cached is not None:
        return cached
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    path = os.path.join(root, "deepspeed_tpu", "telemetry", "traceparse.py")
    spec = importlib.util.spec_from_file_location("dstpu_traceparse", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    # One instance per process: a tool importing another tool (or tests
    # loading several) must see the same COLLECTIVE_RE/CATEGORIES objects.
    sys.modules["dstpu_traceparse"] = mod
    return mod


_tp = _load_traceparse()


def load_breakdowns(run_dir: str) -> List[Dict[str, Any]]:
    """Every host's devicetime breakdown under the run dir (unreadable
    files skipped — a torn atomic rewrite must not kill the report)."""
    out = []
    for path in sorted(glob.glob(os.path.join(run_dir, BREAKDOWN_GLOB))):
        try:
            with open(path) as f:
                out.append(json.load(f))
        except (OSError, ValueError):
            continue
    return out


def _fmt_pct(v: Optional[float]) -> str:
    return f"{v:.1%}" if v is not None else "n/a"


def render_breakdown(bd: Dict[str, Any]) -> str:
    cats = bd.get("categories_sec", {})
    busy = bd.get("busy_sec") or 0.0
    verdicts = (bd.get("roofline") or {}).get("verdicts", {})
    out = [f"host {bd.get('host', '?')} — capture @ step {bd.get('step')} "
           f"({bd.get('steps_captured')} step(s), "
           f"{bd.get('n_devices')} device row(s))"]
    hdr = f"  {'category':<14} {'ms':>10} {'of busy':>8}  verdict"
    out.append(hdr)
    out.append("  " + "-" * (len(hdr) - 2))
    for cat in list(_tp.CATEGORIES) + ["gap"]:
        sec = bd.get("gap_sec", 0.0) if cat == "gap" else cats.get(cat, 0.0)
        share = (sec / busy) if busy > 0 else 0.0
        verdict = "host-dispatch" if cat == "gap" \
            else verdicts.get(cat, "?")
        out.append(f"  {cat:<14} {sec * 1e3:>10.2f} {share:>8.1%}  "
                   f"{verdict}")
    mfu_m, mfu_mod = bd.get("mfu_measured"), bd.get("mfu_modeled")
    out.append(f"  mfu: measured {_fmt_pct(mfu_m)} vs modeled "
               f"{_fmt_pct(mfu_mod)}")
    exp = bd.get("exposed_comm") or {}
    out.append(f"  exposed comm: measured {_fmt_pct(exp.get('measured_frac'))}"
               f" vs modeled {_fmt_pct(exp.get('modeled_frac'))} "
               f"({(exp.get('exposed_sec') or 0.0) * 1e3:.2f} ms exposed of "
               f"{(exp.get('collective_sec') or 0.0) * 1e3:.2f} ms "
               f"collective)")
    hot = bd.get("top_ops") or []
    if hot:
        out.append("  hottest ops (Pallas-tier candidates):")
        for r in hot:
            out.append(f"    {r['name']:<32} {r['sec'] * 1e3:>9.2f} ms "
                       f"x{r['count']:<5} {r['category']}")
    return "\n".join(out)


def render_analysis(analysis: Dict[str, Any], top: int = 10) -> str:
    """Raw --profile-dir rendering (no engine join: categories, overlap,
    hottest ops — the measured half only)."""
    out = [f"measured device time — {len(analysis['captures'])} capture(s), "
           f"{analysis['n_devices']} device row(s)"]
    busy = analysis["busy_sec"] or 0.0
    for cat in _tp.CATEGORIES:
        sec = analysis["categories"][cat]
        share = (sec / busy) if busy > 0 else 0.0
        out.append(f"  {cat:<14} {sec * 1e3:>10.2f} ms {share:>8.1%}")
    out.append(f"  {'gap':<14} {analysis['gap_sec'] * 1e3:>10.2f} ms "
               f"(host-dispatch)")
    window = analysis["window_sec"]
    frac = (analysis["exposed_collective_sec"] / window) if window > 0 \
        else 0.0
    exposed_ms = analysis["exposed_collective_sec"] * 1e3
    coll_ms = analysis["collective_sec"] * 1e3
    out.append(f"  exposed comm: {exposed_ms:.2f} ms of {coll_ms:.2f} ms "
               f"collective ({frac:.1%} of the device window)")
    for r in _tp.top_ops(analysis, top):
        out.append(f"  hot: {r['name']:<32} {r['sec'] * 1e3:>9.2f} ms "
                   f"x{r['count']} ({r['category']})")
    return "\n".join(out)


# ---------------------------------------------------------------------------
# Selftest
# ---------------------------------------------------------------------------

def _selftest() -> int:
    """Synthesize a gzip perfetto capture with known overlap, run the full
    parse→render path, and verify the exposed-comm math and category
    mapping — exercised from the test suite and CI."""
    # Device 0: compute (dot) on stream 1 covers [0, 10ms]; a collective
    # on stream 2 spans [5ms, 15ms] -> 5ms exposed of 10ms collective.
    # Device 1: one fusion [0, 4ms]; runtime noise must be ignored.
    events = [
        {"name": "process_name", "ph": "M", "pid": 1, "tid": 0,
         "args": {"name": "/device:TPU:0"}},
        {"name": "process_name", "ph": "M", "pid": 2, "tid": 0,
         "args": {"name": "/device:TPU:1"}},
        {"name": "process_name", "ph": "M", "pid": 9, "tid": 0,
         "args": {"name": "/host:CPU"}},
        {"name": "dot.1", "ph": "X", "pid": 1, "tid": 1, "ts": 0.0,
         "dur": 10_000.0},
        {"name": "all-reduce.7", "ph": "X", "pid": 1, "tid": 2,
         "ts": 5_000.0, "dur": 10_000.0},
        {"name": "fusion.3", "ph": "X", "pid": 2, "tid": 1, "ts": 0.0,
         "dur": 4_000.0},
        {"name": "transpose.9", "ph": "X", "pid": 2, "tid": 1,
         "ts": 4_000.0, "dur": 1_000.0},
        # host-side runtime scaffolding: never attributed
        {"name": "TfrtCpuExecutable::Execute", "ph": "X", "pid": 9,
         "tid": 1, "ts": 0.0, "dur": 50_000.0},
    ]
    with tempfile.TemporaryDirectory() as td:
        cap = os.path.join(td, "plugins", "profile", "2026_01_01")
        os.makedirs(cap)
        with gzip.open(os.path.join(cap, "host.trace.json.gz"), "wt") as f:
            json.dump({"traceEvents": events}, f)
        # a torn capture next to it must be tolerated
        with open(os.path.join(cap, "torn.trace.json.gz"), "wb") as f:
            f.write(b"\x1f\x8b\x08\x00garbage")
        analysis = _tp.parse_capture_dir(td)
        text = render_analysis(analysis)
    assert analysis["n_devices"] == 2, analysis["n_devices"]
    c = analysis["categories"]
    assert abs(c["matmul"] - 0.010) < 1e-9, c
    assert abs(c["collective"] - 0.010) < 1e-9, c
    assert abs(c["elementwise"] - 0.004) < 1e-9, c
    assert abs(c["copy"] - 0.001) < 1e-9, c
    assert c["other"] == 0.0, c
    assert abs(analysis["exposed_collective_sec"] - 0.005) < 1e-9, analysis
    # busy: dev0 union [0,15] + dev1 [0,5]; windows 15 + 5; no gaps
    assert abs(analysis["busy_sec"] - 0.020) < 1e-9
    assert abs(analysis["window_sec"] - 0.020) < 1e-9
    assert analysis["gap_sec"] < 1e-12
    assert len(analysis["captures"]) == 1        # torn file skipped
    assert "dot.1" in text and "exposed comm" in text
    # breakdown rendering (the engine-written artifact)
    bd = {"format": 1, "step": 40, "host": "hostA", "steps_captured": 2,
          "n_devices": 2,
          "categories_sec": dict(analysis["categories"]),
          "gap_sec": analysis["gap_sec"], "busy_sec": analysis["busy_sec"],
          "window_sec": analysis["window_sec"], "step_time_sec": 0.01,
          "top_ops": _tp.top_ops(analysis, 3),
          "roofline": {"intensity_flops_per_byte": 120.0,
                       "ridge_flops_per_byte": 240.0,
                       "verdicts": {"matmul": "hbm-bound",
                                    "elementwise": "hbm-bound",
                                    "copy": "hbm-bound",
                                    "collective": "network-bound",
                                    "other": "mixed"}},
          "mfu_measured": 0.41, "mfu_modeled": 0.44,
          "exposed_comm": {"collective_sec": 0.010, "exposed_sec": 0.005,
                           "measured_frac": 0.25, "modeled_frac": 0.02},
          "captures": ["x.trace.json.gz"]}
    with tempfile.TemporaryDirectory() as td:
        with open(os.path.join(td, "devicetime_breakdown.hostA.json"),
                  "w") as f:
            json.dump(bd, f)
        loaded = load_breakdowns(td)
    assert len(loaded) == 1
    btext = render_breakdown(loaded[0])
    assert "hbm-bound" in btext and "network-bound" in btext
    assert "41.0%" in btext and "25.0%" in btext
    print(text)
    print()
    print(btext)
    print("\nselftest ok")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("run_dir", nargs="?",
                    help="the job's telemetry.dir (devicetime breakdown "
                         "files)")
    ap.add_argument("--profile-dir",
                    help="parse raw jax.profiler captures "
                         "(*.trace.json.gz) directly instead")
    ap.add_argument("--top", type=int, default=10,
                    help="hottest-op rows for --profile-dir mode")
    ap.add_argument("--json", action="store_true",
                    help="emit the merged report as JSON")
    ap.add_argument("--selftest", action="store_true",
                    help="run the built-in round-trip check and exit")
    args = ap.parse_args(argv)
    if args.selftest:
        return _selftest()
    if args.profile_dir:
        analysis = _tp.parse_capture_dir(args.profile_dir)
        if args.json:
            print(json.dumps(analysis, indent=1))
        else:
            print(render_analysis(analysis, top=args.top))
        return 0
    if not args.run_dir:
        ap.error("run dir required (or --profile-dir / --selftest)")
    breakdowns = load_breakdowns(args.run_dir)
    if args.json:
        print(json.dumps(breakdowns, indent=1))
        return 0
    if not breakdowns:
        print(f"no {BREAKDOWN_GLOB} under {args.run_dir} — is "
              f"telemetry.devicetime enabled and has a capture closed?")
        return 1
    print("\n\n".join(render_breakdown(bd) for bd in breakdowns))
    return 0


if __name__ == "__main__":
    sys.exit(main())
