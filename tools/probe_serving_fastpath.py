"""Acceptance probe: the paged-KV decode fast path is correct and cheaper.

Three claims of docs/SERVING.md "Decode fast path", measured on a tiny
GPT over the CPU backend (Pallas interpreter for the kernel):

1. **Token identity** — the same mixed request trace produces
   byte-identical outputs with the fast path fully off (PR-8 gather
   program), with the paged decode-attention kernel forced, with the
   prefix cache on, and with speculative decoding on. Every fast-path
   piece is a pure-performance lever.
2. **Prefix reuse works** — a shared-prompt-head workload drives
   ``serving/prefix_hits`` above zero and adopted blocks above zero, and
   released/cleared refcounts drain the pool completely (leak check).
3. **Capped fallback shrinks gathered bytes** — under
   ``decode_attention: auto`` (no TPU -> capped gather), the decode
   program's key window covers the max ACTIVE length instead of the full
   ``max_blocks`` table: the modeled gathered-positions total drops
   measurably on the same trace.

Run: JAX_PLATFORMS=cpu python tools/probe_serving_fastpath.py [--selftest]
(tier-1 via tests/test_serving_fastpath.py)
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_ROOT = os.path.join(os.path.dirname(os.path.abspath(__file__)), os.pardir)
sys.path.insert(0, _ROOT)

TRACE = [(5, 10), (9, 4), (3, 8), (12, 5), (7, 7)]


def _build(params_model, **overrides):
    import jax.numpy as jnp

    import deepspeed_tpu
    from deepspeed_tpu.config.config import ServingConfig
    from deepspeed_tpu.serving import ServeEngine

    model, params = params_model
    scfg = ServingConfig(**{"max_batch_size": 2, "kv_block_size": 4,
                            "kv_num_blocks": 64, "max_model_len": 48,
                            **overrides})
    eng = deepspeed_tpu.init_inference(model, params=params,
                                       dtype=jnp.float32)
    return ServeEngine(eng, config=scfg)


def _run_trace(srv, prompts, outs):
    rids = [srv.submit(p, n) for p, n in zip(prompts, outs)]
    res = srv.run_until_complete()
    return [res[r]["tokens"] for r in rids]


def main(argv=None) -> int:
    selftest = "--selftest" in (argv if argv is not None else sys.argv[1:])
    import jax
    import jax.numpy as jnp
    import numpy as np

    from deepspeed_tpu.models import make_gpt

    model, cfg = make_gpt("tiny", dropout_rate=0.0, max_seq_len=64,
                          dtype=jnp.float32)
    params = model.init({"params": jax.random.PRNGKey(0),
                         "dropout": jax.random.PRNGKey(1)},
                        {"input_ids": np.zeros((1, 8), np.int32)})["params"]
    pm = (model, params)
    rng = np.random.default_rng(7)
    prompts = [rng.integers(0, cfg.vocab_size, (t,)).tolist()
               for t, _ in TRACE]
    outs = [n for _, n in TRACE]

    # -- 1. token identity across every fast-path configuration --------
    base_srv = _build(pm)
    base = _run_trace(base_srv, prompts, outs)
    rows = [("off (gather)", base_srv)]
    for name, over in (
            ("kernel", {"decode_attention": "kernel"}),
            ("auto (capped gather)", {"decode_attention": "auto"}),
            ("prefix_cache", {"prefix_cache": True}),
            ("speculative k=3", {"spec_decode": True, "spec_k": 3}),
            ("all on", {"decode_attention": "kernel", "prefix_cache": True,
                        "spec_decode": True, "spec_k": 3})):
        srv = _build(pm, **over)
        got = _run_trace(srv, prompts, outs)
        assert got == base, f"{name}: outputs diverged from the off path"
        rows.append((name, srv))
    print("token identity: every configuration matches the off path "
          f"({len(TRACE)} requests)")
    print(f"{'config':24s} {'kernel steps':>12s} {'gathered pos':>12s} "
          f"{'spec acc/prop':>14s}")
    for name, srv in rows:
        st = srv.stats
        print(f"{name:24s} {st['kernel_steps']:12d} "
              f"{st['gathered_positions']:12d} "
              f"{st['spec_accepted']:6d}/{st['spec_proposed']:<6d}")

    # -- 2. prefix reuse + refcount leak check --------------------------
    head = rng.integers(0, cfg.vocab_size, (16,)).tolist()
    srv = _build(pm, prefix_cache=True)
    warm_prompts = [head + rng.integers(0, cfg.vocab_size, (3,)).tolist()
                    for _ in range(4)]
    _run_trace(srv, warm_prompts, [6] * 4)
    hits, reused = srv.prefix_cache.hits, srv.prefix_cache.blocks_reused
    assert hits > 0, "shared-head workload produced no prefix hits"
    assert reused > 0, "no blocks were adopted"
    held = srv.pool.used_blocks
    assert held == srv.prefix_cache.nodes, (
        f"leak: {held} blocks held vs {srv.prefix_cache.nodes} cache nodes "
        f"after drain")
    srv.prefix_cache.clear()
    assert srv.pool.used_blocks == 0, "pool not empty after cache clear"
    print(f"prefix reuse: {hits} hits, {reused} blocks adopted, pool "
          f"drains to 0 after clear")

    # -- 3. capped fallback gathers measurably less ---------------------
    off = base_srv.stats
    capped = dict(rows)["auto (capped gather)"].stats
    assert capped["full_positions"] == off["gathered_positions"], \
        "traces not comparable"
    ratio = capped["gathered_positions"] / max(1, off["gathered_positions"])
    print(f"capped fallback: {capped['gathered_positions']} vs "
          f"{off['gathered_positions']} gathered key positions "
          f"({ratio:.2f}x)")
    assert ratio < 0.7, (
        f"capped gather should cut gathered positions well below the "
        f"full window on this trace, measured {ratio:.2f}x")

    if selftest:
        print("selftest ok")
    return 0


if __name__ == "__main__":
    sys.exit(main())
