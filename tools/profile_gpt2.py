"""Component-level profile of the GPT-2 bench config (VERDICT r2 task 2).

Decomposes the 267 ms train step into its big pieces by timing jitted
sub-programs at the exact bench shapes (B=16, S=512, gas=4, GPT-2 small),
plus XLA cost_analysis bytes/flops so HBM-bound phases are identifiable.
Writes findings to stdout; tools/run_profile.sh tees into PROFILE_raw.txt.

Also attempts a jax.profiler trace (may be unsupported through the axon
tunnel — failures are reported, not fatal).
"""

import functools
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.models import make_gpt
from deepspeed_tpu.models.gpt import cross_entropy_with_ignore, shift_labels


def log(msg):
    print(msg, flush=True)


def fence(out):
    """Close the timing window with a scalar fetch — block_until_ready does
    not reliably fence the axon tunnel (see bench.py methodology)."""
    leaf = jax.tree_util.tree_leaves(out)[0]
    return float(jnp.sum(leaf).astype(jnp.float32))


def timeit(fn, *args, iters=20, warmup=3):
    out = None
    for _ in range(warmup):
        out = fn(*args)
    fence(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    fence(out)
    return (time.perf_counter() - t0) / iters


def analyze(fn, *args, name=""):
    lowered = jax.jit(fn).lower(*args)
    compiled = lowered.compile()
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, list):
            ca = ca[0]
        flops = ca.get("flops", 0.0)
        bytes_acc = ca.get("bytes accessed", 0.0)
        log(f"[cost] {name}: flops={flops/1e12:.2f}T bytes={bytes_acc/1e9:.2f}GB "
            f"(ridge: {flops/max(bytes_acc,1):.0f} flop/byte)")
    except Exception as e:  # noqa: BLE001
        log(f"[cost] {name}: cost_analysis failed: {e}")
    return compiled


def main():
    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"
    log(f"device: {dev.device_kind} ({dev.platform})")
    B, S, GAS = (16, 512, 4) if on_tpu else (2, 128, 2)
    model, cfg = make_gpt("gpt2" if on_tpu else "tiny", dropout_rate=0.0,
                          remat=False, max_seq_len=max(S, 128))
    D, V, L, H = cfg.hidden_size, cfg.vocab_size, cfg.num_layers, cfg.num_heads
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, V, (B, S), dtype=np.int32))
    batch = {"input_ids": ids}
    params = model.init({"params": jax.random.PRNGKey(0),
                         "dropout": jax.random.PRNGKey(1)}, batch)["params"]
    params = jax.tree_util.tree_map(lambda x: x.astype(jnp.float32), params)
    n_params = sum(int(np.prod(x.shape)) for x in jax.tree_util.tree_leaves(params))
    tokens = B * S
    step_flops = (6.0 * n_params + 12.0 * L * D * S) * tokens
    log(f"model: {n_params/1e6:.0f}M params, {step_flops/1e12:.2f} TFLOP per "
        f"fwd+bwd microbatch (B={B} S={S})")

    def loss_fn(p, b):
        out = model.apply({"params": p}, b, deterministic=True)
        return out["loss"]

    # --- 1. full fwd+bwd microbatch ------------------------------------
    grad_fn = jax.jit(jax.value_and_grad(loss_fn))
    analyze(jax.value_and_grad(loss_fn), params, batch, name="fwd+bwd")
    t_fwdbwd = timeit(grad_fn, params, batch)
    log(f"[time] fwd+bwd microbatch: {t_fwdbwd*1e3:.1f} ms "
        f"-> {step_flops/t_fwdbwd/1e12:.1f} TFLOP/s")

    # --- 2. fwd only ----------------------------------------------------
    fwd = jax.jit(loss_fn)
    t_fwd = timeit(fwd, params, batch)
    log(f"[time] fwd only: {t_fwd*1e3:.1f} ms")

    # --- 3. trunk only (no loss head): mean of final hidden -------------
    def trunk_loss(p, b):
        out = model.apply({"params": p}, b, deterministic=True)
        # logits are produced; sum them cheaply? No — that keeps the head.
        return out["loss"]

    # Instead: a model clone whose head is removed is intrusive; approximate
    # by timing the head in isolation at the same shapes.
    x = jnp.asarray(rng.normal(size=(B, S, D)), jnp.bfloat16)
    wte = params["wte"].astype(jnp.float32)
    labels = jnp.asarray(rng.integers(0, V, (B, S), dtype=np.int32))

    def head_loss(wte_, x_):
        logits = jnp.einsum("bsd,vd->bsv", x_.astype(jnp.bfloat16),
                            wte_.astype(jnp.bfloat16),
                            preferred_element_type=jnp.float32)
        return cross_entropy_with_ignore(logits, labels)

    head_grad = jax.jit(jax.value_and_grad(head_loss, argnums=(0, 1)))
    analyze(jax.value_and_grad(head_loss, argnums=(0, 1)), wte, x,
            name="xent head fwd+bwd (fp32 logits)")
    t_head = timeit(head_grad, wte, x)
    head_flops = 6.0 * V * D * tokens
    log(f"[time] xent head fwd+bwd: {t_head*1e3:.1f} ms "
        f"({100*t_head/t_fwdbwd:.0f}% of microbatch; matmul-only would be "
        f"{head_flops/1e12:.2f} TFLOP -> {head_flops/t_head/1e12:.1f} TFLOP/s)")

    # --- 4. head with bf16 logits + fp32 logsumexp ----------------------
    def head_loss_bf16(wte_, x_):
        logits = jnp.einsum("bsd,vd->bsv", x_.astype(jnp.bfloat16),
                            wte_.astype(jnp.bfloat16))  # bf16 out
        logits = logits.astype(jnp.float32)
        valid = labels >= 0
        lse = jax.nn.logsumexp(logits, axis=-1)
        picked = jnp.take_along_axis(logits, labels[..., None], axis=-1)[..., 0]
        nll = jnp.where(valid, lse - picked, 0.0)
        return nll.sum() / jnp.maximum(valid.sum(), 1)

    head_grad16 = jax.jit(jax.value_and_grad(head_loss_bf16, argnums=(0, 1)))
    t_head16 = timeit(head_grad16, wte, x)
    log(f"[time] xent head bf16-logits: {t_head16*1e3:.1f} ms")

    # --- 5. attention fwd+bwd at bench shape, flash vs xla --------------
    from deepspeed_tpu.ops.transformer.attention import attention
    dh = D // H
    q = jnp.asarray(rng.normal(size=(B, S, H, dh)), jnp.bfloat16)
    k = jnp.asarray(rng.normal(size=(B, S, H, dh)), jnp.bfloat16)
    v = jnp.asarray(rng.normal(size=(B, S, H, dh)), jnp.bfloat16)

    for impl in ("pallas", "xla") if on_tpu else ("xla",):
        def attn_loss(q_, k_, v_, impl=impl):
            return attention(q_, k_, v_, causal=True, impl=impl).astype(
                jnp.float32).sum()

        g = jax.jit(jax.grad(attn_loss, argnums=(0, 1, 2)))
        try:
            t = timeit(g, q, k, v)
            # one layer's attention; model has L of them
            log(f"[time] attention fwd+bwd ({impl}): {t*1e3:.2f} ms/layer "
                f"-> x{L} = {t*L*1e3:.1f} ms ({100*t*L/t_fwdbwd:.0f}% of "
                f"microbatch)")
        except Exception as e:  # noqa: BLE001
            log(f"[time] attention ({impl}) failed: {e}")

    # --- 6. MLP + qkv matmuls sanity: one block fwd+bwd -----------------
    from deepspeed_tpu.models.gpt import GPTBlock
    blk = GPTBlock(cfg)
    bp = blk.init({"params": jax.random.PRNGKey(0)}, x, None, True)["params"]

    def blk_loss(p_, x_):
        return blk.apply({"params": p_}, x_, None, True).astype(jnp.float32).sum()

    gblk = jax.jit(jax.grad(blk_loss, argnums=(0, 1)))
    t_blk = timeit(gblk, bp, x)
    blk_flops = 6.0 * (12 * D * D) * tokens + 12.0 * D * S * tokens
    log(f"[time] one block fwd+bwd: {t_blk*1e3:.2f} ms -> x{L} = "
        f"{t_blk*L*1e3:.1f} ms ({100*t_blk*L/t_fwdbwd:.0f}% of microbatch; "
        f"{blk_flops/t_blk/1e12:.1f} TFLOP/s)")

    # --- 7. embedding fwd+bwd -------------------------------------------
    wpe = params["wpe"].astype(jnp.float32)

    def embed_loss(wte_, wpe_):
        xx = wte_[ids].astype(jnp.bfloat16) + wpe_[:S][None].astype(jnp.bfloat16)
        return xx.astype(jnp.float32).sum()

    gemb = jax.jit(jax.grad(embed_loss, argnums=(0, 1)))
    t_emb = timeit(gemb, wte, wpe)
    log(f"[time] embedding fwd+bwd (gather/scatter): {t_emb*1e3:.2f} ms "
        f"({100*t_emb/t_fwdbwd:.0f}% of microbatch)")

    # --- 8. optimizer apply at GPT-2 scale ------------------------------
    from deepspeed_tpu.ops.adam.fused_adam import FusedAdam
    opt = FusedAdam(lr=1e-4)
    ost = opt.init(params)
    grads = jax.tree_util.tree_map(lambda p: jnp.ones_like(p, jnp.float32), params)

    def apply_fn(g, o, p):
        return opt.update(g, o, p, lr=jnp.float32(1e-4))

    japply = jax.jit(apply_fn)
    t_apply = timeit(japply, grads, ost, params)
    full_step = GAS * t_fwdbwd + t_apply
    log(f"[time] optimizer apply: {t_apply*1e3:.1f} ms "
        f"(amortized 1/{GAS} per microbatch)")
    log(f"[model] gas*{t_fwdbwd*1e3:.1f} + {t_apply*1e3:.1f} = "
        f"{full_step*1e3:.1f} ms/step -> "
        f"{GAS*step_flops/full_step/1e12:.1f} TFLOP/s overall")

    # --- 9. real capture -> measured attribution ------------------------
    # One parser in the tree: the capture round-trips through
    # telemetry/traceparse.py (the same module the devicetime observatory
    # and the report tools use) instead of a hand-rolled scan.
    try:
        from deepspeed_tpu.telemetry import traceparse
        trace_dir = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "profiles", "gpt2")
        with jax.profiler.trace(trace_dir):
            for _ in range(3):
                out = grad_fn(params, batch)
            jax.block_until_ready(out)
        log(f"[trace] written to {trace_dir}")
        analysis = traceparse.parse_capture_dir(trace_dir)
        log(f"[trace] measured attribution over "
            f"{len(analysis['captures'])} capture(s), "
            f"{analysis['n_devices']} device row(s): busy "
            f"{analysis['busy_sec'] * 1e3:.1f} ms, gap "
            f"{analysis['gap_sec'] * 1e3:.1f} ms")
        for cat in traceparse.CATEGORIES:
            sec = analysis["categories"][cat]
            if sec > 0:
                log(f"[trace]   {cat:<12} {sec * 1e3:>10.2f} ms")
        for r in traceparse.top_ops(analysis, 10):
            log(f"[trace]   hot: {r['name']:<32} {r['sec'] * 1e3:>9.2f} ms "
                f"x{r['count']} ({r['category']})")
    except Exception as e:  # noqa: BLE001
        log(f"[trace] jax.profiler failed (axon tunnel): {e}")


if __name__ == "__main__":
    main()
