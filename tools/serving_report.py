#!/usr/bin/env python
"""Render a serving run's SLO metrics from its telemetry JSONL.

The serving-side companion of goodput_report/fleet_report/memory_report:
feed it the run dir (the job's ``telemetry.dir``; docs/SERVING.md) or a
metrics file and it aggregates the ``serving/*`` rows the
:class:`ServeEngine` emits —

- **TTFT** (``serving/ttft_ms`` histogram observations) -> p50/p90/p99 —
  the user-facing latency SLO;
- **throughput** (``serving/tokens_per_sec`` gauge — the engine emits a
  CUMULATIVE token-weighted rate, total decoded tokens / total decode
  seconds) -> overall (final cumulative value, averaged across host
  files) and peak running rate;
- **batch occupancy** (``serving/batch_occupancy``) -> mean/p10 — how
  full the decode batch ran (the continuous-batching win over static
  batching is this number);
- **KV pressure** (``serving/kv_blocks_in_use`` peak,
  ``serving/preempted_seqs`` total) and **queueing**
  (``serving/queue_depth`` mean/max);
- completion counts (``serving/requests_completed``).

    python tools/serving_report.py /runs/serve17/telemetry
    python tools/serving_report.py /runs/serve17/telemetry --json
    python tools/serving_report.py --selftest

Standalone on purpose: stdlib only, so it runs anywhere the run dir
lands (including hosts without jax installed). Keep the tag strings in
sync with deepspeed_tpu/serving/engine.py SERVING_METRIC_TAGS —
tests/test_doc_lint.py pins them.
"""

import argparse
import glob
import json
import os
import sys
import tempfile
from typing import Any, Dict, List

DEFAULT_METRICS_FILE = "metrics.jsonl"
# Request-observatory records (telemetry/requests.py, one JSON object per
# finished request, host-scoped like the metrics file) — the source of the
# TTFT/TPOT/e2e percentile columns. tools/slo_report.py renders the full
# per-request breakdown; here they ride next to the aggregate gauges.
DEFAULT_REQUESTS_FILE = "requests.jsonl"

HIST_TAGS = ("serving/ttft_ms",)
GAUGE_TAGS = (
    "serving/tokens_per_sec",
    "serving/batch_occupancy",
    "serving/kv_blocks_in_use",
    "serving/queue_depth",
    # decode fast path (docs/SERVING.md "Decode fast path")
    "serving/decode_attn_kernel",
    "serving/spec_accept_rate",
    "serving/spec_tokens_per_verify",
)
COUNTER_TAGS = (
    "serving/preempted_seqs",
    "serving/requests_completed",
    "serving/prefix_hits",
    "serving/prefix_blocks_reused",
)


def _percentile(sorted_vals: List[float], q: float) -> float:
    """Linear-interpolated percentile over an already-sorted list."""
    if not sorted_vals:
        return float("nan")
    if len(sorted_vals) == 1:
        return sorted_vals[0]
    pos = (q / 100.0) * (len(sorted_vals) - 1)
    lo = int(pos)
    hi = min(lo + 1, len(sorted_vals) - 1)
    return sorted_vals[lo] + (sorted_vals[hi] - sorted_vals[lo]) * (pos - lo)


def _iter_rows(path: str):
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                row = json.loads(line)
            except json.JSONDecodeError:
                continue          # torn tail line of a live/killed run
            if isinstance(row, dict) and "tag" in row:
                yield row


def _collect_request_latency(run_dir: str,
                             requests_file: str) -> Dict[str, Any]:
    """Percentile columns from the request observatory's records — every
    ``requests*.jsonl`` in the run dir (multi-host runs host-scope the
    name, same as the metrics file). Empty when the run had
    ``telemetry.requests`` off."""
    stem, ext = os.path.splitext(requests_file)
    paths = sorted(glob.glob(os.path.join(run_dir, f"{stem}*{ext}")))
    records: List[Dict[str, Any]] = []
    for path in paths:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    row = json.loads(line)
                except json.JSONDecodeError:
                    continue      # torn tail line of a live/killed run
                if isinstance(row, dict) and "rid" in row \
                        and "e2e_ms" in row:
                    records.append(row)

    def pcts(key):
        vals = sorted(float(r[key]) for r in records
                      if r.get(key) is not None)
        if not vals:
            return None
        return {"p50": _percentile(vals, 50), "p90": _percentile(vals, 90),
                "p99": _percentile(vals, 99)}

    return {"files": [os.path.basename(p) for p in paths],
            "n_requests": len(records),
            "ttft_ms": pcts("ttft_ms"),
            "tpot_ms": pcts("tpot_mean_ms"),
            "e2e_ms": pcts("e2e_ms")}


def collect(run_dir_or_file: str,
            metrics_file: str = DEFAULT_METRICS_FILE,
            requests_file: str = DEFAULT_REQUESTS_FILE) -> Dict[str, Any]:
    """Aggregate serving/* rows from one metrics file or every
    ``metrics*.jsonl`` in a run dir (multi-host runs host-scope the
    name), plus request-record percentile columns when the run dir holds
    ``requests*.jsonl``."""
    request_latency = None
    if os.path.isdir(run_dir_or_file):
        stem, ext = os.path.splitext(metrics_file)
        paths = sorted(glob.glob(
            os.path.join(run_dir_or_file, f"{stem}*{ext}")))
        request_latency = _collect_request_latency(run_dir_or_file,
                                                   requests_file)
        if not request_latency["n_requests"]:
            request_latency = None
    else:
        paths = [run_dir_or_file]
    series: Dict[str, List[float]] = {}
    counters: Dict[str, float] = {}
    n_rows = 0
    for path in paths:
        if not os.path.exists(path):
            continue
        # Counters emit their RUNNING TOTAL: within one host's file the
        # max IS the final count (never double-count rows), while
        # distinct host-scoped files are distinct engines whose finals
        # must SUM.
        per_file: Dict[str, float] = {}
        last_tps = None
        for row in _iter_rows(path):
            tag = row["tag"]
            if not tag.startswith("serving/"):
                continue
            n_rows += 1
            val = float(row.get("value", 0.0))
            if tag in COUNTER_TAGS:
                per_file[tag] = max(per_file.get(tag, 0.0), val)
            else:
                if tag == "serving/tokens_per_sec":
                    last_tps = val        # cumulative rate: last = final
                series.setdefault(tag, []).append(val)
        for tag, val in per_file.items():
            counters[tag] = counters.get(tag, 0.0) + val
        if last_tps is not None:
            series.setdefault("_tps_final_per_file", []).append(last_tps)

    report: Dict[str, Any] = {"files": [os.path.basename(p) for p in paths],
                              "n_rows": n_rows}
    ttft = sorted(series.get("serving/ttft_ms", []))
    report["requests_with_ttft"] = len(ttft)
    report["ttft_ms"] = {"p50": _percentile(ttft, 50),
                         "p90": _percentile(ttft, 90),
                         "p99": _percentile(ttft, 99)} if ttft else None
    tps = series.get("serving/tokens_per_sec", [])
    finals = series.get("_tps_final_per_file", [])
    report["tokens_per_sec"] = {
        # the gauge is a cumulative token-weighted rate: the final value
        # per host file IS that host's run throughput, and distinct
        # hosts' engines SUM (like the counters above)
        "overall": sum(finals),
        "peak": max(tps)} if tps else None
    occ = sorted(series.get("serving/batch_occupancy", []))
    report["batch_occupancy"] = {
        "mean": sum(occ) / len(occ),
        "p10": _percentile(occ, 10)} if occ else None
    blocks = series.get("serving/kv_blocks_in_use", [])
    report["kv_blocks_in_use_peak"] = max(blocks) if blocks else None
    queue = series.get("serving/queue_depth", [])
    report["queue_depth"] = {
        "mean": sum(queue) / len(queue),
        "max": max(queue)} if queue else None
    report["preempted_seqs"] = counters.get("serving/preempted_seqs", 0.0)
    report["requests_completed"] = counters.get(
        "serving/requests_completed", 0.0)
    # -- decode fast path (rows appear only when the piece emitted) -----
    kern = series.get("serving/decode_attn_kernel", [])
    report["decode_attn_kernel_frac"] = (
        sum(kern) / len(kern)) if kern else None
    report["prefix_hits"] = counters.get("serving/prefix_hits")
    report["prefix_blocks_reused"] = counters.get(
        "serving/prefix_blocks_reused")
    acc = series.get("serving/spec_accept_rate", [])
    tpv = series.get("serving/spec_tokens_per_verify", [])
    # both gauges are cumulative rates: the last value IS the run's
    report["spec_accept_rate"] = acc[-1] if acc else None
    report["spec_tokens_per_verify"] = tpv[-1] if tpv else None
    report["request_latency"] = request_latency
    return report


def render(report: Dict[str, Any]) -> str:
    out = ["serving SLO report"]
    out.append(f"  files: {', '.join(report['files']) or '<none>'} "
               f"({report['n_rows']} serving rows)")
    if report["ttft_ms"]:
        t = report["ttft_ms"]
        out.append(f"  TTFT            p50 {t['p50']:9.1f} ms   "
                   f"p90 {t['p90']:9.1f} ms   p99 {t['p99']:9.1f} ms  "
                   f"({report['requests_with_ttft']} requests)")
    if report["tokens_per_sec"]:
        t = report["tokens_per_sec"]
        out.append(f"  throughput      overall {t['overall']:8.1f} tok/s   "
                   f"peak {t['peak']:8.1f} tok/s")
    if report["batch_occupancy"]:
        o = report["batch_occupancy"]
        out.append(f"  occupancy       mean {o['mean']:8.1%}   "
                   f"p10 {o['p10']:8.1%}")
    if report["kv_blocks_in_use_peak"] is not None:
        out.append(f"  KV blocks peak  {report['kv_blocks_in_use_peak']:.0f}"
                   f"   preempted {report['preempted_seqs']:.0f}")
    if report["queue_depth"]:
        q = report["queue_depth"]
        out.append(f"  queue depth     mean {q['mean']:8.2f}   "
                   f"max {q['max']:.0f}")
    kf = report.get("decode_attn_kernel_frac")
    if kf is not None:
        out.append(f"  decode kernel   {kf:8.1%} of decode steps")
    if report.get("prefix_hits") is not None:
        reused = report.get("prefix_blocks_reused") or 0
        out.append(f"  prefix reuse    {report['prefix_hits']:.0f} hits   "
                   f"{reused:.0f} blocks adopted")
    acc = report.get("spec_accept_rate")
    if acc is not None:
        tpv = report.get("spec_tokens_per_verify") or 0
        out.append(f"  speculative     accept {acc:8.1%}   "
                   f"{tpv:.2f} tokens/verify")
    rl = report.get("request_latency")
    if rl:
        out.append(f"  request records {rl['n_requests']} requests "
                   f"({', '.join(rl['files'])}; full breakdown: "
                   f"tools/slo_report.py)")
        for label, key in (("rec TTFT", "ttft_ms"), ("rec TPOT", "tpot_ms"),
                           ("rec e2e", "e2e_ms")):
            p = rl.get(key)
            if p:
                out.append(f"  {label:<9}     p50 {p['p50']:9.1f} ms   "
                           f"p90 {p['p90']:9.1f} ms   "
                           f"p99 {p['p99']:9.1f} ms")
    out.append(f"  completed       {report['requests_completed']:.0f} "
               f"requests")
    if not report["n_rows"]:
        out.append("  (no serving/* rows found — was the engine run with "
                   "telemetry enabled?)")
    return "\n".join(out)


# ---------------------------------------------------------------------------
# Selftest
# ---------------------------------------------------------------------------

def _selftest() -> int:
    """Synthesize a serving metrics JSONL (two host-scoped files, a torn
    tail line) and assert the aggregation: TTFT percentiles, occupancy
    mean, counter totals max-within-file / summed-across-hosts."""
    with tempfile.TemporaryDirectory() as td:
        rows_a = [
            {"tag": "serving/ttft_ms", "value": float(v), "step": i,
             "kind": "histogram"}
            for i, v in enumerate((10, 20, 30, 40, 50, 60, 70, 80, 90, 100))
        ] + [
            {"tag": "serving/batch_occupancy", "value": 0.75, "step": 1,
             "kind": "gauge"},
            {"tag": "serving/batch_occupancy", "value": 0.25, "step": 2,
             "kind": "gauge"},
            {"tag": "serving/tokens_per_sec", "value": 100.0, "step": 1,
             "kind": "gauge"},
            {"tag": "serving/tokens_per_sec", "value": 300.0, "step": 2,
             "kind": "gauge"},
            {"tag": "serving/kv_blocks_in_use", "value": 17, "step": 2,
             "kind": "gauge"},
            {"tag": "serving/queue_depth", "value": 3, "step": 1,
             "kind": "gauge"},
            {"tag": "serving/preempted_seqs", "value": 2, "step": 2,
             "kind": "counter"},
            {"tag": "serving/requests_completed", "value": 5, "step": 2,
             "kind": "counter"},
            # decode fast path rows
            {"tag": "serving/decode_attn_kernel", "value": 1.0, "step": 1,
             "kind": "gauge"},
            {"tag": "serving/decode_attn_kernel", "value": 0.0, "step": 2,
             "kind": "gauge"},
            {"tag": "serving/prefix_hits", "value": 3, "step": 2,
             "kind": "counter"},
            {"tag": "serving/prefix_blocks_reused", "value": 12, "step": 2,
             "kind": "counter"},
            {"tag": "serving/spec_accept_rate", "value": 0.5, "step": 1,
             "kind": "gauge"},
            {"tag": "serving/spec_accept_rate", "value": 0.75, "step": 2,
             "kind": "gauge"},
            {"tag": "serving/spec_tokens_per_verify", "value": 2.5,
             "step": 2, "kind": "gauge"},
            {"tag": "engine/hbm_peak_bytes", "value": 1, "step": 0,
             "kind": "gauge"},                     # non-serving: ignored
        ]
        with open(os.path.join(td, "metrics.hostA.jsonl"), "w") as f:
            for r in rows_a:
                f.write(json.dumps(r) + "\n")
            f.write('{"tag": "torn')               # must be tolerated
        with open(os.path.join(td, "metrics.hostB.jsonl"), "w") as f:
            f.write(json.dumps(
                {"tag": "serving/requests_completed", "value": 3,
                 "step": 2, "kind": "counter"}) + "\n")
            f.write(json.dumps(
                {"tag": "serving/tokens_per_sec", "value": 200.0,
                 "step": 2, "kind": "gauge"}) + "\n")

        report = collect(td)
        assert report["requests_with_ttft"] == 10, report
        assert abs(report["ttft_ms"]["p50"] - 55.0) < 1e-6, report
        assert report["ttft_ms"]["p99"] > 90, report
        assert abs(report["batch_occupancy"]["mean"] - 0.5) < 1e-6
        # cumulative-rate gauge: each file's LAST value is that host's
        # throughput; hosts sum (300 from hostA + 200 from hostB)
        assert report["tokens_per_sec"]["overall"] == 500.0
        assert report["tokens_per_sec"]["peak"] == 300.0
        assert report["kv_blocks_in_use_peak"] == 17
        assert report["preempted_seqs"] == 2
        # running totals: max within a file, summed across host files
        assert report["requests_completed"] == 8
        # fast-path rows: kernel-step fraction is a mean, prefix counters
        # sum like the other counters, spec gauges report the LAST
        # (cumulative) value
        assert abs(report["decode_attn_kernel_frac"] - 0.5) < 1e-6
        assert report["prefix_hits"] == 3
        assert report["prefix_blocks_reused"] == 12
        assert report["spec_accept_rate"] == 0.75
        assert report["spec_tokens_per_verify"] == 2.5
        text = render(report)
        assert "TTFT" in text and "occupancy" in text
        assert "completed" in text
        assert "prefix reuse" in text and "speculative" in text
        assert "decode kernel" in text
        # no request records yet -> no percentile columns
        assert report["request_latency"] is None
        json.dumps(report)                         # serializable

        # request-observatory records (host-scoped, torn tail tolerated)
        # add the TTFT/TPOT/e2e percentile columns
        with open(os.path.join(td, "requests.hostA.jsonl"), "w") as f:
            for i in range(10):
                f.write(json.dumps(
                    {"rid": i, "e2e_ms": 100.0 + 10 * i,
                     "ttft_ms": 10.0 + i,
                     "tpot_mean_ms": 2.0 + 0.2 * i}) + "\n")
            f.write('{"rid": 99, "torn')
        with open(os.path.join(td, "requests.hostB.jsonl"), "w") as f:
            f.write(json.dumps({"rid": 0, "e2e_ms": 500.0,
                                "ttft_ms": None,
                                "tpot_mean_ms": 4.0}) + "\n")
        report = collect(td)
        rl = report["request_latency"]
        assert rl["n_requests"] == 11, rl
        assert abs(rl["e2e_ms"]["p50"] - 150.0) < 1e-6, rl
        assert rl["e2e_ms"]["p99"] > 190.0, rl
        assert abs(rl["ttft_ms"]["p50"] - 14.5) < 1e-6, rl  # None skipped
        assert abs(rl["tpot_ms"]["p50"] - 3.0) < 1e-6, rl
        text = render(report)
        assert "rec TPOT" in text and "rec e2e" in text
        json.dumps(report)
    print("\nselftest ok")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("run_dir", nargs="?",
                    help="the job's telemetry.dir (or a metrics JSONL "
                         "file)")
    ap.add_argument("--metrics-file", default=DEFAULT_METRICS_FILE)
    ap.add_argument("--requests-file", default=DEFAULT_REQUESTS_FILE)
    ap.add_argument("--json", action="store_true",
                    help="machine-readable report")
    ap.add_argument("--selftest", action="store_true",
                    help="run the built-in round-trip check and exit")
    args = ap.parse_args(argv)
    if args.selftest:
        return _selftest()
    if not args.run_dir:
        ap.error("run dir required (or --selftest)")
    report = collect(args.run_dir, metrics_file=args.metrics_file,
                     requests_file=args.requests_file)
    if args.json:
        print(json.dumps(report, indent=1, sort_keys=True))
    else:
        print(render(report))
    return 0


if __name__ == "__main__":
    sys.exit(main())
