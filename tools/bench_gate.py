#!/usr/bin/env python
"""Perf regression gate: compare a bench JSON against a committed baseline.

The bench trajectory went dark for two rounds (BENCH_r04/r05) with
nothing gating regressions — a comm- or kernel-level change whose win (or
loss) is real must be *measured*, and a measured loss must fail loudly.
This gate compares ``bench.py``'s per-section result rows (the
``"sections"`` block every bench JSON now carries) against a committed
baseline with per-section noise-floored thresholds:

- a section's effective threshold is ``max(--threshold, noise floor)`` —
  the floors encode the measured run-to-run drift of the shared-tunnel
  TPU rounds (±10%, VAR_probe r3), so ordinary jitter never cries wolf;
- throughput/MFU metrics regress when they DROP beyond the threshold;
  latency metrics (``ttft``/``*_ms``) regress when they RISE;
- exit code 2 on any regression (0 clean, 1 usage/missing-file) — the
  distinct rc the bench driver can branch on;
- ``--update-baseline`` rewrites the baseline from the candidate after a
  deliberate perf change landed.

Pre-``sections`` bench JSONs (BENCH_r01..r05) are still comparable: their
known flat keys map onto sections via ``_LEGACY_KEYS``.

Stdlib-only (json, argparse) so it runs in any CI context, and
``--selftest`` (tier-1) proves the gate passes a clean run and catches an
injected regression with a nonzero rc.

Usage:
    python tools/bench_gate.py BENCH.json [--baseline BENCH_baseline.json]
    python tools/bench_gate.py BENCH.json --update-baseline
    python tools/bench_gate.py --selftest
"""

import argparse
import json
import os
import re
import sys
import tempfile
from typing import Any, Dict, List

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEFAULT_BASELINE = os.path.join(REPO, "BENCH_baseline.json")

# Per-section relative noise floors. Measured: the shared axon tunnel
# shows +-10% run-to-run drift (VAR_probe, r3); the 16k row runs few
# steps (coarser timing); serving TTFT percentiles ride scheduler jitter.
NOISE_FLOORS = {
    "bert128": 0.10,
    "bert512": 0.10,
    "gpt2": 0.10,
    "gpt2_dropout": 0.10,
    "long16k": 0.12,
    "inference": 0.10,
    "serving": 0.15,
    # dispatch A/B: tiny model, few steps per window -> coarse timing
    "moe_gpt": 0.12,
    # optimizer-step A/B: sub-ms windows on a ~1M-param tree
    "fused_optimizer": 0.15,
}
DEFAULT_FLOOR = 0.10

# Metrics where SMALLER is better (latency-shaped); everything else is
# throughput-shaped (bigger is better).
_LOWER_BETTER_RE = re.compile(r"ttft|latency|_ms$")

# Flat-key -> (section, metric) map for bench JSONs that predate the
# sections schema.
_LEGACY_KEYS = {
    "value": ("bert128", "samples_per_sec"),
    "tflops": ("bert128", "tflops"),
    "mfu": ("bert128", "mfu"),
    "bert_seq512_samples_per_sec": ("bert512", "samples_per_sec"),
    "gpt2_tokens_per_sec": ("gpt2", "tokens_per_sec"),
    "gpt2_mfu": ("gpt2", "mfu"),
    "gpt2_dropout_tokens_per_sec": ("gpt2_dropout", "tokens_per_sec"),
    "gpt2_dropout_mfu": ("gpt2_dropout", "mfu"),
    "gpt2_seq16k_dense_tokens_per_sec": ("long16k", "dense_tokens_per_sec"),
    "gpt2_seq16k_bigbird_tokens_per_sec":
        ("long16k", "bigbird_tokens_per_sec"),
    "gpt2_seq16k_sparse_speedup": ("long16k", "sparse_speedup"),
    "gpt2_generate_b1_tokens_per_sec": ("inference", "b1_tokens_per_sec"),
    "gpt2_generate_b8_tokens_per_sec": ("inference", "b8_tokens_per_sec"),
    "serving_tokens_per_sec": ("serving", "tokens_per_sec"),
    "serving_ttft_p50_ms": ("serving", "ttft_p50_ms"),
    "serving_ttft_p99_ms": ("serving", "ttft_p99_ms"),
    "serving_mean_occupancy": ("serving", "mean_occupancy"),
}


def sections_of(doc: Dict[str, Any]) -> Dict[str, Dict[str, float]]:
    """The per-section metric rows of one bench JSON: the ``sections``
    block when present (bench.py emits it), else the legacy flat keys
    mapped through ``_LEGACY_KEYS``. Non-numeric values are dropped."""
    raw = doc.get("sections")
    if not isinstance(raw, dict):
        raw = {}
        for key, (section, metric) in _LEGACY_KEYS.items():
            if doc.get(key) is not None:
                raw.setdefault(section, {})[metric] = doc[key]
    out: Dict[str, Dict[str, float]] = {}
    for section, rows in raw.items():
        if not isinstance(rows, dict):
            continue
        for metric, value in rows.items():
            if metric == "partial":
                # Row annotation (bench.py: timing lost windows to a
                # transient failure), not a metric — never gated.
                continue
            if isinstance(value, (int, float)) and not isinstance(
                    value, bool):
                out.setdefault(section, {})[metric] = float(value)
    return out


def lower_is_better(metric: str) -> bool:
    return bool(_LOWER_BETTER_RE.search(metric))


def compare(baseline: Dict[str, Any], candidate: Dict[str, Any],
            threshold: float = 0.05) -> Dict[str, Any]:
    """Row-by-row comparison. Only metrics present in BOTH are judged; a
    section/metric missing from the candidate is reported (a silently
    vanished bench row is itself suspicious) but is not a regression —
    partial bench records are a designed-for state."""
    base_s = sections_of(baseline)
    cand_s = sections_of(candidate)
    rows: List[Dict[str, Any]] = []
    missing: List[str] = []
    for section in sorted(base_s):
        floor = NOISE_FLOORS.get(section, DEFAULT_FLOOR)
        thr = max(float(threshold), floor)
        for metric in sorted(base_s[section]):
            old = base_s[section][metric]
            new = cand_s.get(section, {}).get(metric)
            if new is None:
                missing.append(f"{section}/{metric}")
                continue
            if old == 0:
                continue                      # no meaningful ratio
            delta = (new - old) / abs(old)
            if lower_is_better(metric):
                verdict = ("REGRESSION" if delta > thr
                           else "improvement" if delta < -thr else "ok")
            else:
                verdict = ("REGRESSION" if delta < -thr
                           else "improvement" if delta > thr else "ok")
            rows.append({"section": section, "metric": metric,
                         "baseline": old, "value": new,
                         "delta_frac": delta, "threshold": thr,
                         "verdict": verdict})
    new_metrics = sorted(
        f"{s}/{m}" for s in cand_s for m in cand_s[s]
        if m not in base_s.get(s, {}))
    regressions = [r for r in rows if r["verdict"] == "REGRESSION"]
    return {"rows": rows, "missing": missing, "new_metrics": new_metrics,
            "n_regressions": len(regressions), "ok": not regressions}


def render(report: Dict[str, Any]) -> str:
    out = []
    hdr = (f"{'section':<14} {'metric':<26} {'baseline':>12} {'value':>12} "
           f"{'delta':>8} {'thresh':>7}  verdict")
    out.append(hdr)
    out.append("-" * len(hdr))
    for r in report["rows"]:
        out.append(
            f"{r['section']:<14} {r['metric']:<26} {r['baseline']:>12.4g} "
            f"{r['value']:>12.4g} {r['delta_frac']:>+7.1%} "
            f"{r['threshold']:>6.0%}  {r['verdict']}")
    if report["missing"]:
        out.append("")
        out.append("missing from candidate (rows the baseline has): "
                   + ", ".join(report["missing"]))
    if report["new_metrics"]:
        out.append("")
        out.append("new in candidate (not yet in baseline): "
                   + ", ".join(report["new_metrics"]))
    out.append("")
    out.append("GATE: " + ("ok" if report["ok"] else
                           f"{report['n_regressions']} REGRESSION(S)"))
    return "\n".join(out)


def update_baseline(candidate_path: str, baseline_path: str) -> None:
    with open(candidate_path) as f:
        doc = json.load(f)
    base = {
        "source": os.path.basename(candidate_path),
        "metric": doc.get("metric"),
        "environment": doc.get("environment"),
        "sections": sections_of(doc),
    }
    tmp = baseline_path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(base, f, indent=1, sort_keys=True)
    os.replace(tmp, baseline_path)


# ---------------------------------------------------------------------------
# Selftest
# ---------------------------------------------------------------------------

def _selftest() -> int:
    baseline = {"sections": {
        "gpt2": {"tokens_per_sec": 147691.0, "mfu": 0.60},
        "serving": {"tokens_per_sec": 900.0, "ttft_p50_ms": 12.0},
    }}
    # 1. clean run: inside every noise floor -> rc 0
    ok_run = {"sections": {
        "gpt2": {"tokens_per_sec": 143000.0, "mfu": 0.59},
        "serving": {"tokens_per_sec": 880.0, "ttft_p50_ms": 13.0},
    }}
    rep = compare(baseline, ok_run)
    assert rep["ok"], rep
    # 2. injected throughput regression (-30%) -> caught
    bad_run = {"sections": {
        "gpt2": {"tokens_per_sec": 103000.0, "mfu": 0.60},
        "serving": {"tokens_per_sec": 900.0, "ttft_p50_ms": 12.0},
    }}
    rep = compare(baseline, bad_run)
    assert not rep["ok"] and rep["n_regressions"] == 1, rep
    assert rep["rows"][0]["metric"] != "ttft_p50_ms"
    # 3. latency direction: TTFT doubling is a regression even though the
    #    number went UP
    slow_serve = {"sections": {
        "gpt2": {"tokens_per_sec": 147691.0, "mfu": 0.60},
        "serving": {"tokens_per_sec": 900.0, "ttft_p50_ms": 24.0},
    }}
    rep = compare(baseline, slow_serve)
    bad = [r for r in rep["rows"] if r["verdict"] == "REGRESSION"]
    assert len(bad) == 1 and bad[0]["metric"] == "ttft_p50_ms", rep
    # 4. missing section reported, not failed; new metric surfaced
    partial = {"sections": {"gpt2": {"tokens_per_sec": 150000.0,
                                     "mfu": 0.61, "extra_row": 1.0}}}
    rep = compare(baseline, partial)
    assert rep["ok"]
    assert "serving/tokens_per_sec" in rep["missing"]
    assert "gpt2/extra_row" in rep["new_metrics"]
    # 4b. a whole ADDED section (new bench row family absent from the
    #     baseline — e.g. a PR that grows bench.py a numerics section) is
    #     informational, never a regression: the gate stays green and the
    #     rows surface under new_metrics so --update-baseline adopts them
    #     deliberately.
    added = {"sections": {
        "gpt2": {"tokens_per_sec": 147691.0, "mfu": 0.60},
        "serving": {"tokens_per_sec": 900.0, "ttft_p50_ms": 12.0},
        "numerics_probe": {"overhead_x": 1.02, "flush_fetch_ms": 0.4},
    }}
    rep = compare(baseline, added)
    assert rep["ok"] and rep["n_regressions"] == 0, rep
    assert "numerics_probe/overhead_x" in rep["new_metrics"], rep
    assert "numerics_probe/flush_fetch_ms" in rep["new_metrics"], rep
    text_added = render(rep)
    assert "new in candidate" in text_added and "GATE: ok" in text_added
    # 4c. the moe_gpt dispatch A/B section (bench.py sec_moe_gpt): new
    #     against an old baseline it is informational; once adopted, its
    #     step-time rows gate in the latency direction (a slower
    #     all-to-all is a regression even though the number went UP) and
    #     the static dispatch-bytes row gates as throughput-shaped only
    #     on real change.
    moe_rows = {"step_time_einsum_ms": 80.0, "step_time_scatter_ms": 75.0,
                "step_time_alltoall_ms": 70.0,
                "alltoall_vs_scatter_speedup": 1.07,
                "dispatch_bytes_ici_per_layer": 166400.0,
                "capacity_overflow_frac": 0.10}
    with_moe = {"sections": {**baseline["sections"], "moe_gpt": moe_rows}}
    rep = compare(baseline, with_moe)
    assert rep["ok"], rep
    assert "moe_gpt/step_time_alltoall_ms" in rep["new_metrics"], rep
    moe_base = {"sections": {"moe_gpt": moe_rows}}
    slow_a2a = {"sections": {"moe_gpt": {
        **moe_rows, "step_time_alltoall_ms": 95.0}}}
    rep = compare(moe_base, slow_a2a)
    bad = [r for r in rep["rows"] if r["verdict"] == "REGRESSION"]
    assert len(bad) == 1 and bad[0]["metric"] == "step_time_alltoall_ms", rep
    # 4d. kernel tier round 2 rows (bench.py bench_serving_chunked /
    #     bench_fused_optimizer): the serving chunked A/B rows and the
    #     fused_optimizer section are informational against an old
    #     baseline; once adopted, all of them are _ms rows and gate in
    #     the latency direction (a slower chunked mixed step or fused
    #     update is a regression even though the number went UP).
    k2_serving = {**baseline["sections"]["serving"],
                  "mixed_step_bucketed_ms": 9.0,
                  "mixed_step_chunked_ms": 7.0,
                  "ttft_p99_bucketed_ms": 120.0,
                  "ttft_p99_chunked_ms": 60.0}
    k2_fused = {"optimizer_step_xla_ms": 2.0,
                "optimizer_step_fused_ms": 1.5}
    with_k2 = {"sections": {**baseline["sections"],
                            "serving": k2_serving,
                            "fused_optimizer": k2_fused}}
    rep = compare(baseline, with_k2)
    assert rep["ok"], rep
    assert "serving/mixed_step_chunked_ms" in rep["new_metrics"], rep
    assert "fused_optimizer/optimizer_step_fused_ms" in rep["new_metrics"], \
        rep
    k2_base = {"sections": {"serving": k2_serving,
                            "fused_optimizer": k2_fused}}
    slow_k2 = {"sections": {
        "serving": {**k2_serving, "ttft_p99_chunked_ms": 110.0},
        "fused_optimizer": {**k2_fused, "optimizer_step_fused_ms": 2.5}}}
    rep = compare(k2_base, slow_k2)
    bad = sorted(r["metric"] for r in rep["rows"]
                 if r["verdict"] == "REGRESSION")
    assert bad == ["optimizer_step_fused_ms", "ttft_p99_chunked_ms"], rep
    # 5. legacy flat-key bench JSONs map onto sections
    legacy = sections_of({"value": 532.98, "gpt2_tokens_per_sec": 147691.0,
                          "serving_ttft_p50_ms": 9.1, "metric": "x",
                          "errors": ["not-a-number"]})
    assert legacy["bert128"]["samples_per_sec"] == 532.98
    assert legacy["serving"]["ttft_p50_ms"] == 9.1
    # 6. the full CLI round-trip: update-baseline, pass, then fail rc 2
    with tempfile.TemporaryDirectory() as td:
        cand = os.path.join(td, "bench.json")
        basep = os.path.join(td, "BENCH_baseline.json")
        with open(cand, "w") as f:
            json.dump({"metric": "m", "sections": baseline["sections"]}, f)
        assert main([cand, "--baseline", basep, "--update-baseline"]) == 0
        assert main([cand, "--baseline", basep]) == 0
        with open(cand, "w") as f:
            json.dump(bad_run, f)
        rc = main([cand, "--baseline", basep])
        assert rc == 2, rc
        text = render(compare(baseline, bad_run))
    assert "REGRESSION" in text and "GATE:" in text
    print(text)
    print("\nselftest ok")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("bench", nargs="?",
                    help="candidate bench JSON (bench.py stdout line or "
                         "BENCH_partial.json)")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help=f"committed baseline (default: {DEFAULT_BASELINE})")
    ap.add_argument("--threshold", type=float, default=0.05,
                    help="relative regression threshold; per-section noise "
                         "floors raise it (default 0.05)")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite the baseline from the candidate and exit")
    ap.add_argument("--json", action="store_true",
                    help="emit the comparison as JSON")
    ap.add_argument("--selftest", action="store_true",
                    help="run the built-in gate check and exit")
    args = ap.parse_args(argv)
    if args.selftest:
        return _selftest()
    if not args.bench:
        ap.error("bench JSON required (or --selftest)")
    if not os.path.exists(args.bench):
        print(f"bench file not found: {args.bench}", file=sys.stderr)
        return 1
    if args.update_baseline:
        update_baseline(args.bench, args.baseline)
        print(f"[bench_gate] baseline <- {args.bench} ({args.baseline})")
        return 0
    if not os.path.exists(args.baseline):
        print(f"baseline not found: {args.baseline} (seed one with "
              f"--update-baseline)", file=sys.stderr)
        return 1
    with open(args.baseline) as f:
        baseline = json.load(f)
    with open(args.bench) as f:
        candidate = json.load(f)
    report = compare(baseline, candidate, threshold=args.threshold)
    if args.json:
        print(json.dumps(report, indent=1))
    else:
        print(render(report))
    return 0 if report["ok"] else 2


if __name__ == "__main__":
    sys.exit(main())
