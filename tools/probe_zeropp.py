"""Acceptance probe: the ZeRO++ weight path's modeled param-gather
traffic (ISSUE 12 — qwZ quantized weight all-gather + hpZ hierarchical
secondary partition; arXiv 2306.10209, weight-update sharding 2004.13336).

Builds a 2-slice virtual mesh (dcn=2 x data=4 on 8 CPU devices), wires a
2-layer tiny GPT through the engine at each weight-path tier and reports
the modeled per-device param-hop bytes per optimizer step
(comm/grad_sync.py ``ParamGatherPlan.modeled_bytes`` — the same numbers
the ``comm/bytes_dcn_params`` / ``comm/bytes_ici_params`` gauges emit):

- **off** — a zeropp-less stage-3 engine. Its param hop is modeled as
  the *global-primary* fp32 gather (partition over the full dcn x data
  world — what production ZeRO-3 pays, and what the hpZ trade is
  measured against; the engine itself shards intra-slice, so the row is
  the comparison baseline, not this engine's live traffic).
- **hpZ** — ``zeropp.hpz: on`` with the fp32 passthrough wire: the
  explicit gather rides ICI only. Asserts cross-slice param bytes == 0.
- **qwZ-int8** — hpZ + ``quantized_weights: int8``: asserts >= 3.5x
  modeled param-gather compression vs the fp32 wire (blockwise int8's
  analytic ratio is 4/(1 + 4/block) ~ 3.94 at block 256).

Every tier also trains a tiny GPT on one fixed batch (finite, decreasing
loss; the quantized tier within 5% of the implicit path), and the int8
engine runs with the numerics observatory on so the probe can gate the
measured ``numerics/param_quant_rel_err`` < 1e-1 — the end-to-end error
of the lossy param hop.

Run: JAX_PLATFORMS=cpu python tools/probe_zeropp.py [--selftest]
(--selftest shrinks the trajectory; same assertions).
"""

import argparse
import json
import os
import shutil
import sys
import tempfile

os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") + \
    " --xla_force_host_platform_device_count=8"
_ROOT = os.path.join(os.path.dirname(os.path.abspath(__file__)), os.pardir)
sys.path.insert(0, _ROOT)

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

import deepspeed_tpu  # noqa: E402
from deepspeed_tpu.comm.grad_sync import ParamGatherPlan  # noqa: E402
from deepspeed_tpu.parallel.mesh import build_mesh  # noqa: E402
from deepspeed_tpu.runtime.zero.config import (ZeroConfig,  # noqa: E402
                                               ZeroPPConfig)
from deepspeed_tpu.runtime.zero.partition import ZeroPartitioner  # noqa: E402

SEQ = 16
BLOCK = 256


def build_engine(zeropp=None, telemetry=None, num_layers=2, gas=2):
    from deepspeed_tpu.models import make_gpt

    model, cfg = make_gpt("tiny", num_layers=num_layers, dropout_rate=0.0,
                          dtype=jnp.float32)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, cfg.vocab_size, (8, SEQ), dtype=np.int32)
    params = model.init({"params": jax.random.PRNGKey(0),
                         "dropout": jax.random.PRNGKey(1)},
                        {"input_ids": ids})["params"]
    zcfg = {"stage": 3, "stage3_param_persistence_threshold": 0}
    if zeropp is not None:
        zcfg["zeropp"] = zeropp
    config = {
        "train_micro_batch_size_per_gpu": 1,
        "gradient_accumulation_steps": gas,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "zero_optimization": zcfg,
        "steps_per_print": 1 if telemetry else 10_000,
    }
    if telemetry:
        config["telemetry"] = telemetry
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, params=params, mesh=build_mesh(slices=2),
        config=config)
    return engine, cfg


def modeled_row(engine, label, block):
    """Per-device per-step modeled param-hop bytes for this tier. The
    `off` engine has no plan — model its hop as the GLOBAL fp32 primary
    gather (partition over the full dcn x data world), the production
    ZeRO-3 baseline the hpZ/qwZ rows are measured against."""
    if engine.param_gather_plan is not None:
        m = engine.param_gather_plan.modeled_bytes()
    else:
        zpp = ZeroPPConfig(quantized_weights="off", hpz="off",
                           quant_block_size=block)
        # Global-primary specs for the SAME param tree: a partitioner
        # whose zeropp block is active with hpz off spans (dcn, data).
        zc = ZeroConfig()
        zc.stage = 3
        zc.param_persistence_threshold = 0
        zc.zeropp = ZeroPPConfig(quantized_weights="bf16", hpz="off",
                                 quant_block_size=block)
        part = ZeroPartitioner(engine.mesh, zc)
        specs = part.param_specs(engine.state.params, engine._base_specs)
        m = ParamGatherPlan(zpp, engine.mesh,
                            param_template=engine.state.params,
                            param_specs=specs).modeled_bytes()
    return {"tier": label, **m}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--selftest", action="store_true",
                    help="short trajectory, same assertions")
    ap.add_argument("--steps", type=int, default=6)
    ap.add_argument("--block", type=int, default=BLOCK)
    args = ap.parse_args()
    steps = 3 if args.selftest else args.steps

    # Telemetry scratch dir for the int8 engine's numerics flush —
    # removed at exit like probe_comm's capture dirs (no temp litter
    # from tier-1 runs).
    tdir = tempfile.mkdtemp(prefix="probe_zeropp_")
    import atexit
    atexit.register(shutil.rmtree, tdir, ignore_errors=True)
    tiers = [
        ("off", None, None),
        ("hpZ", {"hpz": "on", "quant_block_size": args.block}, None),
        ("qwZ-int8", {"hpz": "on", "quantized_weights": "int8",
                      "quant_block_size": args.block},
         {"enabled": True, "dir": tdir, "numerics": {"enabled": True}}),
    ]
    engines, rows, losses = {}, [], {}
    cfg = None
    sinks = {}
    for label, zeropp, telemetry in tiers:
        engines[label], cfg = build_engine(zeropp, telemetry,
                                           gas=2)
        rows.append(modeled_row(engines[label], label, args.block))
        if telemetry:
            from deepspeed_tpu.telemetry.registry import InMemorySink
            sinks[label] = engines[label].telemetry.registry.add_sink(
                InMemorySink())

    rng = np.random.default_rng(1)
    # One fixed batch, trained repeatedly: random-token loss on FRESH
    # batches hovers at ln(vocab) regardless of learning — a fixed batch
    # must memorize, so "loss decreases" is a meaningful gate.
    ids = rng.integers(0, cfg.vocab_size, (2, 16, SEQ), dtype=np.int32)
    for label in engines:
        losses[label] = []
    for _ in range(steps):
        for label, engine in engines.items():
            losses[label].append(
                float(engine.train_batch({"input_ids": ids.copy()})))

    by_tier = {r["tier"]: r for r in rows}
    off_dcn = by_tier["off"]["bytes_dcn_params"]
    hpz_dcn = by_tier["hpZ"]["bytes_dcn_params"]
    int8_ratio = by_tier["qwZ-int8"]["compression_ratio"]

    print(f"{'tier':>9} {'dcn bytes/step':>15} {'ici bytes/step':>15} "
          f"{'vs fp32':>8} {'final loss':>11}")
    for r in rows:
        t = r["tier"]
        print(f"{t:>9} {r['bytes_dcn_params']:>15,} "
              f"{r['bytes_ici_params']:>15,} "
              f"{r['compression_ratio']:>7.2f}x {losses[t][-1]:>11.4f}")

    ok = True
    if off_dcn <= 0:
        print("FAIL: the global-primary baseline models no cross-slice "
              "param bytes — nothing for hpZ to eliminate")
        ok = False
    if hpz_dcn != 0:
        print(f"FAIL: hpZ cross-slice param bytes {hpz_dcn} != 0")
        ok = False
    if by_tier["qwZ-int8"]["bytes_dcn_params"] != 0:
        print("FAIL: qwZ-int8 (hpz on) cross-slice param bytes != 0")
        ok = False
    if int8_ratio < 3.5:
        print(f"FAIL: int8 param-gather compression {int8_ratio:.2f}x "
              f"< 3.5x")
        ok = False
    for label, ls in losses.items():
        if not np.isfinite(ls).all():
            print(f"FAIL: {label} non-finite losses {ls}")
            ok = False
        elif ls[-1] >= ls[0]:
            print(f"FAIL: {label} loss not decreasing {ls[0]:.4f} -> "
                  f"{ls[-1]:.4f}")
            ok = False
    drift = np.abs(np.array(losses["qwZ-int8"]) - np.array(losses["off"]))
    rel = (drift / np.abs(losses["off"])).max()
    if rel > 5e-2:
        print(f"FAIL: int8 trajectory drifts {rel:.3f} > 5% from implicit")
        ok = False

    # The measured lossy-hop gate: numerics/param_quant_rel_err < 1e-1 on
    # the int8 tiny-GPT run (the ISSUE 12 acceptance bound; the gauge
    # flushes at steps_per_print=1 cadence).
    qerr_rows = [r["value"] for r in sinks["qwZ-int8"].rows
                 if r["tag"] == "numerics/param_quant_rel_err"]
    qerr = max(qerr_rows) if qerr_rows else None
    if qerr is None:
        print("FAIL: numerics/param_quant_rel_err never emitted")
        ok = False
    elif not (0 < qerr < 1e-1):
        print(f"FAIL: numerics/param_quant_rel_err {qerr} not in (0, 0.1)")
        ok = False

    print(json.dumps({
        "mesh": "dcn2 x data4 (virtual, CPU)",
        "steps": steps,
        "block": args.block,
        "rows": rows,
        "hpz_dcn_param_bytes": int(hpz_dcn),
        "off_dcn_param_bytes": int(off_dcn),
        "ratio_int8_vs_fp32": round(float(int8_ratio), 3),
        "int8_max_rel_loss_drift": round(float(rel), 5),
        "param_quant_rel_err": (round(float(qerr), 6)
                                if qerr is not None else None),
        "pass": ok,
    }))
    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    main()
