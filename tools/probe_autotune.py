#!/usr/bin/env python3
"""Acceptance probe: the autotuner adopts the MEASURED winner.

A tiny two-candidate search on CPU (one engine, one process): the base
config splits its per-chip batch as micro 8 x gas 1; the challenger
re-splits it micro 1 x gas 8 — same global batch (the invariant the
ladder math guarantees), different scan length, measurably different
step time. The search trials both and must adopt whichever MEASURED
faster, with the loser's verdict (eliminated reason, or its trial rank)
recorded in the result — the evidence trail the issue asks for.

Asserts (``--selftest`` — wired into tier-1 via tests/test_autotuning.py):
- both candidates carry a measured step time;
- the adopted candidate is the measured minimum;
- the loser's record carries its rank and, when halved away, the reason;
- the engine leaves the search on the winning config with its pre-search
  step counter intact.

Run: JAX_PLATFORMS=cpu python tools/probe_autotune.py [--selftest]
"""

import json
import os
import sys
import tempfile

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_ROOT = os.path.join(os.path.dirname(os.path.abspath(__file__)), os.pardir)
sys.path.insert(0, _ROOT)
sys.path.insert(0, os.path.join(_ROOT, "tests"))

HIDDEN = 32


def run_probe():
    import numpy as np

    import deepspeed_tpu
    from simple_model import mlp_loss_fn, mlp_params

    td = tempfile.mkdtemp(prefix="probe_autotune_")
    engine, _, _, _ = deepspeed_tpu.initialize(
        loss_fn=mlp_loss_fn, params=mlp_params(hidden=HIDDEN, layers=2),
        config={
            "train_micro_batch_size_per_gpu": 8,
            "gradient_accumulation_steps": 1,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
            "zero_optimization": {"stage": 2},
            "steps_per_print": 10_000,
            "autotuning": {"enabled": True,
                           "zero_stages": [2],
                           "micro_gas": [[8, 1], [1, 8]],
                           "zeropp": ["off"],
                           "top_k": 2, "trial_steps": 4,
                           "trial_warmup": 1},
        }, rng_seed=0)

    rng = np.random.default_rng(0)

    def make_batches(micro, gas):
        return {
            "x": rng.standard_normal((gas, micro, HIDDEN)).astype(
                np.float32),
            "y": rng.standard_normal((gas, micro, 8)).astype(np.float32),
        }

    steps_before = engine.global_steps
    result = deepspeed_tpu.autotune(engine, make_batches, result_dir=td)
    measured = {r["name"]: r["measured_step_ms"]
                for r in result["candidates"]
                if r["measured_step_ms"] is not None}
    loser = next(r for r in result["candidates"]
                 if r["name"] != result["adopted"]["name"])
    return engine, result, measured, loser, steps_before


def main(argv=None) -> int:
    selftest = "--selftest" in (argv or sys.argv[1:])
    engine, result, measured, loser, steps_before = run_probe()

    from deepspeed_tpu.autotuning import render_result_table
    print(render_result_table(result))
    row = {
        "adopted": result["adopted"]["name"],
        "adopted_ms": result["adopted"]["measured_step_ms"],
        "loser": loser["name"],
        "loser_status": loser["status"],
        "loser_ms": loser["measured_step_ms"],
        "loser_rank": loser["rank"],
        "search_sec": result["search_sec"],
    }
    print(json.dumps(row))
    if selftest:
        assert len(result["candidates"]) == 2, result["candidates"]
        assert len(measured) == 2, measured
        # The adopted candidate is the measured minimum — the tuner's
        # whole contract.
        best = min(measured, key=measured.get)
        assert result["adopted"]["name"] == best, (result["adopted"], measured)
        # The loser's verdict is recorded: its rank always, and the
        # halving reason when it was eliminated early.
        assert loser["rank"] is not None, loser
        assert loser["status"] in ("trialed", "eliminated"), loser
        if loser["status"] == "eliminated":
            assert "successive halving" in (loser["reason"] or ""), loser
        # The engine left the search ON the winner with state restored.
        assert engine.global_steps == steps_before, engine.global_steps
        mb, gas = (engine.train_micro_batch_size_per_gpu,
                   engine.gradient_accumulation_steps)
        assert [mb, gas] in ([8, 1], [1, 8]) and mb * gas == 8, (mb, gas)
        assert "result_path" in result and os.path.exists(
            result["result_path"])
        print("selftest ok")
    return 0


if __name__ == "__main__":
    sys.exit(main())
