"""Acceptance probe: kernel tier round 2 is correct and cheaper.

Three claims of docs/PERFORMANCE.md "Kernel tier round 2" /
docs/SERVING.md "Chunked prefill admission", measured on a tiny GPT over
the CPU backend (Pallas interpreter for both kernels):

1. **One compile, lower tail latency** — a bursty burst of prompts whose
   lengths span several prefill buckets is served token-identically by
   the chunked admission mode, its TTFT p99 beats the bucketed path on
   the same cold engines (the bucketed path pays one cold compile per
   bucket inside the burst's latency window), and the recompile detector
   proves the mixed program compiled exactly ONCE while the bucketed
   engine built O(buckets) prefill programs.
2. **Chunked admission is exact** — mid-prompt chunk boundaries, decode
   rows and prefill rows sharing one program: the full greedy traces
   match the bucketed oracle byte for byte.
3. **Fused update preserves the trajectory** — the one-pass blockwise
   Adam kernel steps a real training engine to the same parameters as
   the XLA elementwise chain (the throughput claim is a TPU round's;
   the probe pins the math).

Run: JAX_PLATFORMS=cpu python tools/probe_chunked_prefill.py [--selftest]
(tier-1 via tests/test_chunked_prefill.py)
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_ROOT = os.path.join(os.path.dirname(os.path.abspath(__file__)), os.pardir)
sys.path.insert(0, _ROOT)

# Bursty: lengths span >= 3 prefill buckets, all submitted up front.
LENS = [6, 14, 28, 44, 9, 30]
OUTS = [8, 5, 7, 4, 9, 6]


def _build(params_model, **overrides):
    import jax.numpy as jnp

    import deepspeed_tpu
    from deepspeed_tpu.config.config import ServingConfig
    from deepspeed_tpu.serving import ServeEngine
    from deepspeed_tpu.telemetry import (InMemorySink, MetricsRegistry,
                                         StepTracer, Telemetry)

    model, params = params_model
    scfg = ServingConfig(**{"max_batch_size": 2, "kv_block_size": 4,
                            "kv_num_blocks": 64, "max_model_len": 64,
                            **overrides})
    eng = deepspeed_tpu.init_inference(model, params=params,
                                       dtype=jnp.float32)
    reg = MetricsRegistry()
    reg.add_sink(InMemorySink())
    # The engine's own (enabled-by-default) detector proves the
    # one-compile claim; the registry feeds the TTFT histogram.
    tel = Telemetry(reg, StepTracer(path=None, enabled=False),
                    eng.recompile_detector)
    return ServeEngine(eng, config=scfg, telemetry=tel)


def _run_burst(srv, prompts, outs):
    rids = [srv.submit(p, n) for p, n in zip(prompts, outs)]
    res = srv.run_until_complete()
    toks = [res[r]["tokens"] for r in rids]
    p99 = srv.telemetry.registry.histogram("serving/ttft_ms").percentile(99)
    return toks, p99


def main(argv=None) -> int:
    selftest = "--selftest" in (argv if argv is not None else sys.argv[1:])
    import jax
    import jax.numpy as jnp
    import numpy as np

    from deepspeed_tpu.models import make_gpt

    model, cfg = make_gpt("tiny", dropout_rate=0.0, max_seq_len=80,
                          dtype=jnp.float32)
    params = model.init({"params": jax.random.PRNGKey(0),
                         "dropout": jax.random.PRNGKey(1)},
                        {"input_ids": np.zeros((1, 8), np.int32)})["params"]
    pm = (model, params)
    rng = np.random.default_rng(7)
    prompts = [rng.integers(0, cfg.vocab_size, (n,)).tolist()
               for n in LENS]

    # -- 1 + 2. bucketed oracle vs chunked admission, cold engines ------
    bsrv = _build(pm)
    base, p99_b = _run_burst(bsrv, prompts, OUTS)
    csrv = _build(pm, chunked_prefill=True, chunked_token_budget=16)
    got, p99_c = _run_burst(csrv, prompts, OUTS)
    assert got == base, "chunked admission diverged from the bucketed oracle"
    n_buckets = len(bsrv._prefill_jit) + len(bsrv._tail_prefill_jit)
    det = csrv.engine.recompile_detector
    compiles = det.compiles("serving.mixed_step")
    retraces = det.retraces("serving.mixed_step")
    print(f"token identity: {len(LENS)} bursty requests match the "
          f"bucketed oracle byte for byte")
    print(f"compile count: mixed program {compiles} compile / {retraces} "
          f"retraces vs {n_buckets} bucketed prefill programs")
    assert compiles == 1 and retraces == 0, (
        f"mixed program must compile exactly once "
        f"({compiles} compiles, {retraces} retraces)")
    assert n_buckets >= 2, (
        f"burst was meant to span several buckets (saw {n_buckets})")
    assert len(csrv._prefill_jit) + len(csrv._tail_prefill_jit) == 0, \
        "chunked engine built bucketed prefill programs"
    print(f"TTFT p99: {p99_c:.1f} ms chunked vs {p99_b:.1f} ms bucketed")
    assert p99_c < p99_b, (
        f"chunked TTFT p99 ({p99_c:.1f} ms) should beat bucketed "
        f"({p99_b:.1f} ms) on a cold bursty trace")

    # -- 3. fused update: same trajectory as the XLA chain --------------
    sys.path.insert(0, os.path.join(_ROOT, "tests"))
    from simple_model import mlp_loss_fn, mlp_params, random_batch

    from deepspeed_tpu import initialize
    from deepspeed_tpu.parallel.mesh import build_mesh

    def engine(fused):
        cfg_d = {"train_micro_batch_size_per_gpu": 8,
                 "gradient_accumulation_steps": 1,
                 "optimizer": {"type": "Adam", "params": {"lr": 1e-2},
                               "fused_update": fused},
                 "zero_optimization": {"stage": 2}}
        e, _, _, _ = initialize(loss_fn=mlp_loss_fn, params=mlp_params(),
                                config=cfg_d, mesh=build_mesh())
        return e

    brng = np.random.default_rng(0)
    batches = [random_batch(brng, batch_size=8) for _ in range(3)]
    a, b = engine(False), engine(True)
    for bt in batches:
        for e in (a, b):
            loss = e.forward(bt)
            e.backward(loss)
            e.step()
    err = max(float(jnp.max(jnp.abs(x - y)))
              for x, y in zip(jax.tree_util.tree_leaves(a.state.params),
                              jax.tree_util.tree_leaves(b.state.params)))
    print(f"fused update: ZeRO-2 trajectory max param delta {err:.2e} "
          f"after {len(batches)} steps")
    assert err < 1e-5, f"fused update trajectory diverged ({err:.2e})"

    if selftest:
        print("selftest ok")
    return 0


if __name__ == "__main__":
    sys.exit(main())
