"""Round-3 perf probe — component ablation for the GPT-2 / BERT-seq512 MFU gap.

Methodology (see memory: scalar-fence timings through the axon tunnel):
each variant is one jitted fwd+bwd+adam step; 10 timed steps after 2 warmup,
window closed by a scalar fetch. Analytic FLOPs as in bench.py.

Variants isolate where the time goes:
  full        — model loss as shipped (fp32 [B,S,V] logits + fp32 log_softmax)
  logitsum    — loss = mean(logits) (head matmul paid, CE skipped)
  vocab2048   — full with a tiny vocab (head+CE jointly shrunk)
  xla-attn    — full, attention impl forced to xla
  pallas-attn — full, attention impl forced to pallas
"""

import functools
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

sys.path.insert(0, "/root/repo")
from deepspeed_tpu.models import make_bert, make_gpt  # noqa: E402

PEAK = 197.0


def log(msg):
    print(msg, flush=True)


def fence(x):
    return float(jnp.sum(jax.tree_util.tree_leaves(x)[0]).astype(jnp.float32))


def timed(step, params, opt_state, batch, steps=10, warmup=2):
    for _ in range(warmup):
        params, opt_state, loss = step(params, opt_state, batch)
    fence(loss)
    t0 = time.perf_counter()
    for _ in range(steps):
        params, opt_state, loss = step(params, opt_state, batch)
    fence(loss)
    return (time.perf_counter() - t0) / steps


def run_variant(name, model, params, batch, loss_mode, flops):
    tx = optax.adam(1e-4)
    opt_state = tx.init(params)

    def loss_fn(p):
        out = model.apply({"params": p}, batch, deterministic=True)
        if loss_mode == "full":
            return out["loss"]
        if loss_mode == "logitsum":
            return jnp.mean(out["logits"].astype(jnp.float32))
        raise ValueError(loss_mode)

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def step(p, s, b):
        loss, g = jax.value_and_grad(loss_fn)(p)
        up, s = tx.update(g, s, p)
        return optax.apply_updates(p, up), s, loss

    t0 = time.time()
    dt = timed(step, params, opt_state, batch)
    tf = flops / dt / 1e12
    log(f"[probe] {name:28s} {dt*1e3:7.1f} ms/step  {tf:6.1f} TF/s  "
        f"MFU {tf/PEAK:5.1%}  (compile+run {time.time()-t0:.0f}s)")
    return dt


def flops_for(n_params, tokens, seq, hidden, layers):
    return 6.0 * n_params * tokens + 12.0 * layers * hidden * seq * tokens


def count(tree):
    return sum(int(np.prod(x.shape)) for x in jax.tree_util.tree_leaves(tree))


def probe_gpt():
    bs, seq = 16, 512
    rng = np.random.default_rng(0)
    batch = {"input_ids": rng.integers(0, 50257, (bs, seq), dtype=np.int32)}
    results = {}
    for name, over in [
        ("gpt2 full (auto attn)", {}),
        ("gpt2 xla attn", {"attention_impl": "xla"}),
        ("gpt2 pallas attn", {"attention_impl": "pallas"}),
    ]:
        model, cfg = make_gpt("gpt2", dropout_rate=0.0, remat=False,
                              max_seq_len=512, **over)
        params = model.init({"params": jax.random.PRNGKey(0)},
                            batch, deterministic=True)["params"]
        n = count(params)
        fl = flops_for(n, bs * seq, seq, cfg.hidden_size, cfg.num_layers)
        results[name] = run_variant(name, model, params, batch, "full", fl)
    # head-cost isolation: same model, logits-sum loss (CE skipped)
    model, cfg = make_gpt("gpt2", dropout_rate=0.0, remat=False,
                          max_seq_len=512)
    params = model.init({"params": jax.random.PRNGKey(0)},
                        batch, deterministic=True)["params"]
    n = count(params)
    fl = flops_for(n, bs * seq, seq, cfg.hidden_size, cfg.num_layers)
    results["gpt2 logitsum (no CE)"] = run_variant(
        "gpt2 logitsum (no CE)", model, params, batch, "logitsum", fl)
    # tiny-vocab: isolates embed+head+CE cost jointly (flops adjusted)
    model, cfg = make_gpt("gpt2", dropout_rate=0.0, remat=False,
                          max_seq_len=512, vocab_size=2048)
    batch2 = {"input_ids": rng.integers(0, 2048, (bs, seq), dtype=np.int32)}
    params = model.init({"params": jax.random.PRNGKey(0)},
                        batch2, deterministic=True)["params"]
    n = count(params)
    fl = flops_for(n, bs * seq, seq, cfg.hidden_size, cfg.num_layers)
    results["gpt2 vocab2048 full"] = run_variant(
        "gpt2 vocab2048 full", model, params, batch2, "full", fl)
    return results


def probe_bert():
    bs, seq = 8, 512
    rng = np.random.default_rng(0)
    ids = rng.integers(0, 30522, (bs, seq), dtype=np.int32)
    labels = np.where(rng.random((bs, seq)) < 0.15, ids, -100)
    batch = {"input_ids": ids,
             "attention_mask": np.ones((bs, seq), np.int32),
             "labels": labels.astype(np.int32)}
    for name, over in [
        ("bert512 full (auto attn)", {}),
        ("bert512 xla attn", {"attention_impl": "xla"}),
        ("bert512 pallas attn", {"attention_impl": "pallas"}),
    ]:
        model, cfg = make_bert("bert-large", dropout_rate=0.0, remat=False,
                               max_seq_len=512, **over)
        params = model.init({"params": jax.random.PRNGKey(0),
                             "dropout": jax.random.PRNGKey(1)},
                            batch)["params"]
        n = count(params)
        fl = flops_for(n, bs * seq, seq, cfg.hidden_size, cfg.num_layers)
        run_variant(name, model, params, batch, "full", fl)
    # no-mask variant: does the [B,S] all-ones mask block the flash path or
    # cost anything?
    model, cfg = make_bert("bert-large", dropout_rate=0.0, remat=False,
                           max_seq_len=512)
    b2 = {"input_ids": ids, "labels": labels.astype(np.int32)}
    params = model.init({"params": jax.random.PRNGKey(0),
                         "dropout": jax.random.PRNGKey(1)}, b2)["params"]
    n = count(params)
    fl = flops_for(n, bs * seq, seq, cfg.hidden_size, cfg.num_layers)
    run_variant("bert512 no mask", model, params, b2, "full", fl)


if __name__ == "__main__":
    log(f"devices: {jax.devices()}")
    probe_gpt()
    probe_bert()
