"""Acceptance probe: the hierarchical grad sync's modeled DCN traffic,
plus an overlap A/B mode for the overlapped schedule (ROADMAP item 1).

Builds a 2-slice virtual mesh (dcn=2 x data=4 on 8 CPU devices), wires a
2-layer GPT through the engine at each grad-sync tier — ``off`` (implicit
fp32), ``on`` bf16, ``on`` int8 — and reports the modeled per-device DCN
bytes per optimizer step for each (comm/grad_sync.py ``modeled_bytes``,
the same numbers the ``comm/*`` telemetry gauges emit). Asserts:

- int8 models a >= 3.5x DCN byte reduction vs the fp32 wire (the ISSUE 4
  acceptance bound; blockwise int8's analytic ratio is 8/(1 + 4/block));
- bf16 models ~2x;
- every tier actually trains (finite, decreasing loss on a short run) and
  the quantized tiers stay within tolerance of the implicit path.

The "off" row models the implicit path as fp32 wire on the same
hierarchical schedule — self-shard included on every row, so absolute
bytes are upper bounds while RATIOS between rows are exact. The ladder
engines pin ``overlap_grad_sync: off`` so rows stay byte-comparable
across tiers (the overlapped schedule reduces every microstep over DCN
— gas x the bytes, traded for hiding them).

**Overlap A/B** (``--overlap-ab``, also part of ``--selftest``): two
identical int8 engines, overlap off vs on, on the same 2-slice mesh.
Each variant's step is captured with ``jax.profiler`` and parsed through
``telemetry/traceparse`` into the measured exposed-collective fraction
(the ``comm/measured_exposed_frac`` math) and the LONGEST contiguous
exposed-collective segment. On TPU the fraction itself drops; on the CPU
backend (no async collectives — nothing truly runs concurrently) the
capture proxy is the max exposed segment: the GAS-boundary schedule
exposes one long contiguous collective block, the overlapped schedule
splits it into per-microstep slivers bounded by the last microstep's
share. Asserts the A/B segment ratio and reports wall step times + the
modeled exposed fractions beside the measured ones.

Run: JAX_PLATFORMS=cpu python tools/probe_comm.py
     [--selftest | --overlap-ab]
(--selftest shrinks the trajectory; same assertions).
"""

import argparse
import json
import os
import sys

os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") + \
    " --xla_force_host_platform_device_count=8"
_ROOT = os.path.join(os.path.dirname(os.path.abspath(__file__)), os.pardir)
sys.path.insert(0, _ROOT)

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

import deepspeed_tpu  # noqa: E402
from deepspeed_tpu.comm.grad_sync import GradSyncPlan  # noqa: E402
from deepspeed_tpu.config.config import CommConfig  # noqa: E402
from deepspeed_tpu.parallel.mesh import build_mesh  # noqa: E402

SEQ = 16


def build_engine(comm=None, num_layers=2, gas=2):
    from deepspeed_tpu.models import make_gpt

    model, cfg = make_gpt("tiny", num_layers=num_layers, dropout_rate=0.0,
                          dtype=jnp.float32)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, cfg.vocab_size, (8, SEQ), dtype=np.int32)
    params = model.init({"params": jax.random.PRNGKey(0),
                         "dropout": jax.random.PRNGKey(1)},
                        {"input_ids": ids})["params"]
    config = {
        "train_micro_batch_size_per_gpu": 1,
        "gradient_accumulation_steps": gas,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 2},
        "steps_per_print": 10_000,
    }
    if comm is not None:
        config["comm"] = comm
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, params=params, mesh=build_mesh(slices=2),
        config=config)
    return engine, cfg


def modeled_row(engine, label, block):
    """Per-device per-step modeled bytes for this engine's tier. The
    `off` engine has no plan — model its fp32 wire on the same bucket
    schedule via a bits=32 plan over the same grad tree."""
    if engine.grad_sync_plan is not None:
        m = engine.grad_sync_plan.modeled_bytes()
    else:
        comm = CommConfig(hierarchical="on", dcn_quant_bits=32,
                          quant_block_size=block)
        m = GradSyncPlan(comm, engine.mesh,
                         grad_template=engine.state.grad_acc,
                         grad_specs=engine.grad_specs,
                         acc_dtype=engine.grad_accum_dtype).modeled_bytes()
    return {"tier": label, **m}


def _ab_mlp_loss(params, batch, rng):
    h = jnp.tanh(batch["x"] @ params["w1"])
    return jnp.mean((h @ params["w2"] - batch["y"]) ** 2)


def run_overlap_ab(steps, block, gas=4):
    """Overlap A/B on the 2-slice mesh: wall step time + a real
    jax.profiler capture per variant, parsed through telemetry/traceparse
    into the measured exposed-collective numbers. Returns (rows, ok).

    Uses a small MLP so the A/B compiles in seconds inside tier-1 (the
    GPT hook coverage lives in tests/test_dcn.py's jaxpr tests — the
    measured axis here, the per-microstep DCN dispatch, is
    model-agnostic). The CPU gate is ``dcn_burstiness``
    (traceparse.collective_burstiness): schedule geometry — the share of
    all-to-all wire time concentrated in one burst — which the
    overlapped schedule provably spreads, and which stays meaningful on
    a CPU backend where nothing can truly run concurrently (a 2-core CI
    box cannot demonstrate wall-clock hiding). On TPU read
    ``measured_exposed_frac`` (the ``comm/measured_exposed_frac`` math)
    — with async collectives it is the fraction that must drop toward
    0."""
    import shutil
    import tempfile
    import time

    from deepspeed_tpu.telemetry import traceparse

    variants = [
        ("overlap_off", {"hierarchical": "on", "dcn_quant_bits": 8,
                         "quant_block_size": block,
                         "overlap_grad_sync": "off"}),
        ("overlap_on", {"hierarchical": "on", "dcn_quant_bits": 8,
                        "quant_block_size": block,
                        "overlap_grad_sync": "on"}),
    ]
    rng = np.random.default_rng(2)
    k1, k2 = jax.random.split(jax.random.PRNGKey(0))
    mlp_params = {"w1": jax.random.normal(k1, (16, 64)) * 0.1,
                  "w2": jax.random.normal(k2, (64, 8)) * 0.1}
    batch = {"x": rng.standard_normal((gas, 16, 16)).astype(np.float32),
             "y": rng.standard_normal((gas, 16, 8)).astype(np.float32)}
    rows = []
    for label, comm in variants:
        engine, _, _, _ = deepspeed_tpu.initialize(
            loss_fn=_ab_mlp_loss,
            params=jax.tree_util.tree_map(np.copy, mlp_params),
            mesh=build_mesh(slices=2),
            config={"train_micro_batch_size_per_gpu": 2,
                    "gradient_accumulation_steps": gas,
                    "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
                    "zero_optimization": {"stage": 2},
                    "steps_per_print": 10_000,
                    "comm": comm})
        for _ in range(2):                       # compile + warm
            float(engine.train_batch(batch))
        t0 = time.perf_counter()
        for _ in range(steps):
            loss = engine.train_batch(batch)
        loss = float(loss)
        dt = (time.perf_counter() - t0) / steps
        cap_dir = tempfile.mkdtemp(prefix=f"probe_comm_{label}_")
        try:
            jax.profiler.start_trace(cap_dir)
            for _ in range(3):
                float(engine.train_batch(batch))
            jax.profiler.stop_trace()
            a = traceparse.parse_capture_dir(cap_dir)
            burst = traceparse.collective_burstiness_dir(cap_dir)
        finally:
            shutil.rmtree(cap_dir, ignore_errors=True)
        window = a["window_sec"] or 1e-12
        plan = engine.grad_sync_plan
        rows.append({
            "variant": label,
            "overlap": int(plan.overlap),
            "step_time_ms": round(dt * 1e3, 3),
            "loss": round(loss, 5),
            # The devicetime observatory's gauge math
            # (comm/measured_exposed_frac) — the TPU criterion;
            # rendezvous-dominated on the CPU backend's thread-pool
            # rows, reported for completeness.
            "measured_exposed_frac": round(
                a["exposed_collective_sec"] / window, 4),
            # The CPU-capture proxy: how concentrated the DCN stage's
            # all-to-all wire time is (1-burst boundary sync vs spread
            # per-microstep dispatch).
            "dcn_burstiness": round(burst, 4),
            "collective_sec": round(a["collective_sec"], 5),
            "modeled_exposed_frac_floor": round(
                plan.modeled_exposed_seconds()
                / max(plan.modeled_wire_seconds(), 1e-12), 4),
        })
        del engine

    off, on = rows
    print(f"{'variant':>12} {'step ms':>9} {'meas exposed':>13} "
          f"{'dcn burst':>10} {'modeled floor':>14}")
    for r in rows:
        print(f"{r['variant']:>12} {r['step_time_ms']:>9.2f} "
              f"{r['measured_exposed_frac']:>13.3f} "
              f"{r['dcn_burstiness']:>10.3f} "
              f"{r['modeled_exposed_frac_floor']:>14.3f}")
    ok = True
    # The gate (CPU-capture proxy for comm/measured_exposed_frac): the
    # overlapped schedule must measurably spread the DCN burst.
    if not (on["dcn_burstiness"] < off["dcn_burstiness"]):
        print(f"FAIL: overlap-on dcn burstiness {on['dcn_burstiness']} "
              f"not below overlap-off {off['dcn_burstiness']}")
        ok = False
    if on["modeled_exposed_frac_floor"] >= 1.0:
        print("FAIL: overlapped plan models no hidden wire time")
        ok = False
    if not (np.isfinite(on["loss"]) and np.isfinite(off["loss"])):
        print("FAIL: non-finite A/B losses")
        ok = False
    return rows, ok


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--selftest", action="store_true",
                    help="short trajectory, same assertions")
    ap.add_argument("--overlap-ab", action="store_true",
                    help="only run the overlap A/B (capture-based)")
    ap.add_argument("--steps", type=int, default=8)
    ap.add_argument("--block", type=int, default=256)
    args = ap.parse_args()
    steps = 4 if args.selftest else args.steps

    if args.overlap_ab:
        ab_rows, ok = run_overlap_ab(steps, args.block)
        print(json.dumps({"overlap_ab": ab_rows, "pass": ok}))
        sys.exit(0 if ok else 1)

    # The modeled-bytes ladder pins overlap OFF so rows stay
    # byte-comparable across tiers (see module docstring); the overlap
    # axis is measured separately below.
    tiers = [
        ("off", None),
        ("bf16", {"hierarchical": "on", "dcn_quant_bits": 16,
                  "quant_block_size": args.block,
                  "overlap_grad_sync": "off"}),
        ("int8", {"hierarchical": "on", "dcn_quant_bits": 8,
                  "quant_block_size": args.block,
                  "overlap_grad_sync": "off"}),
    ]
    engines, rows, losses = {}, [], {}
    cfg = None
    for label, comm in tiers:
        engines[label], cfg = build_engine(comm)
        rows.append(modeled_row(engines[label], label, args.block))

    rng = np.random.default_rng(1)
    # One fixed batch, trained repeatedly: random-token loss on FRESH
    # batches hovers at ln(vocab) regardless of learning — a fixed batch
    # must memorize, so "loss decreases" is a meaningful gate.
    ids = rng.integers(0, cfg.vocab_size, (2, 16, SEQ), dtype=np.int32)
    for label in engines:
        losses[label] = []
    for _ in range(steps):
        for label, engine in engines.items():
            losses[label].append(
                float(engine.train_batch({"input_ids": ids.copy()})))

    by_tier = {r["tier"]: r for r in rows}
    fp32_bytes = by_tier["off"]["bytes_dcn"]
    int8_bytes = by_tier["int8"]["bytes_dcn"]
    bf16_bytes = by_tier["bf16"]["bytes_dcn"]
    ratio_int8 = fp32_bytes / int8_bytes
    ratio_bf16 = fp32_bytes / bf16_bytes

    print(f"{'tier':>6} {'bytes_dcn/step':>15} {'vs fp32':>8} "
          f"{'buckets':>8} {'final loss':>11}")
    for r in rows:
        t = r["tier"]
        print(f"{t:>6} {r['bytes_dcn']:>15,} "
              f"{fp32_bytes / r['bytes_dcn']:>7.2f}x "
              f"{r['num_buckets']:>8} {losses[t][-1]:>11.4f}")

    ok = True
    if ratio_int8 < 3.5:
        print(f"FAIL: int8 DCN reduction {ratio_int8:.2f}x < 3.5x")
        ok = False
    if not (1.8 <= ratio_bf16 <= 2.2):
        print(f"FAIL: bf16 DCN reduction {ratio_bf16:.2f}x not ~2x")
        ok = False
    for label, ls in losses.items():
        if not np.isfinite(ls).all():
            print(f"FAIL: {label} non-finite losses {ls}")
            ok = False
        elif ls[-1] >= ls[0]:
            print(f"FAIL: {label} loss not decreasing {ls[0]:.4f} -> "
                  f"{ls[-1]:.4f}")
            ok = False
    drift = np.abs(np.array(losses["int8"]) - np.array(losses["off"]))
    rel = (drift / np.abs(losses["off"])).max()
    if rel > 5e-2:
        print(f"FAIL: int8 trajectory drifts {rel:.3f} > 5% from implicit")
        ok = False

    del engines
    ab_rows, ab_ok = run_overlap_ab(steps, args.block)
    ok = ok and ab_ok

    print(json.dumps({
        "mesh": "dcn2 x data4 (virtual, CPU)",
        "steps": steps,
        "block": args.block,
        "rows": rows,
        "ratio_int8_vs_fp32": round(ratio_int8, 3),
        "ratio_bf16_vs_fp32": round(ratio_bf16, 3),
        "int8_max_rel_loss_drift": round(float(rel), 5),
        "overlap_ab": ab_rows,
        "pass": ok,
    }))
    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    main()
