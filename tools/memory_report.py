#!/usr/bin/env python
"""Merge a run's memory telemetry into ONE per-host memory report.

The memory-side companion of goodput_report/fleet_report: feed it the run
dir (the job's ``telemetry.dir``; docs/OBSERVABILITY.md "Memory
observatory") and it merges, per host,

- the **model-state ledger** gauges (``memory/ledger_*_bytes`` — master /
  optimizer / grads / compute-dtype params per device, from the TrainState
  pytree + ZeRO shardings),
- the **XLA attribution** gauges (``memory/xla_*_bytes`` from
  ``compiled.memory_analysis()`` of the step executable),
- the **HBM watermarks** (``engine/hbm_peak_bytes``,
  ``memory/hbm_headroom_bytes``, ``memory/hbm_limit_bytes``),
- the persisted **capacity plan** (``memory_plan*.json`` — the ZeRO
  stage × offload × microbatch what-if table), and
- any **OOM crashdumps** (``oom_step*/`` directories written by the
  observatory's forensics tier: info/memory/ledger/XLA artifacts),

into one table naming the tightest host and rendering the what-if
projection next to what actually happened.

    python tools/memory_report.py /runs/exp17/telemetry
    python tools/memory_report.py /runs/exp17/telemetry --crashdumps crashdumps
    python tools/memory_report.py /runs/exp17/telemetry --json
    python tools/memory_report.py --selftest

Standalone on purpose: stdlib only, so it runs anywhere the run dir lands
(including hosts without jax installed).
"""

import argparse
import glob
import json
import os
import sys
import tempfile
from typing import Any, Dict, List, Optional

DEFAULT_METRICS_FILE = "metrics.jsonl"

# Keep in sync with deepspeed_tpu/telemetry/memory.py (this tool is
# import-free by design; tests/test_doc_lint.py pins the doc tables to
# the package's MEMORY_METRIC_TAGS).
LEDGER_GAUGES = (
    "memory/ledger_master_bytes",
    "memory/ledger_optimizer_bytes",
    "memory/ledger_grads_bytes",
    "memory/ledger_compute_params_bytes",
    "memory/ledger_scalars_bytes",
    "memory/ledger_device_bytes",
    "memory/ledger_host_bytes",
)
XLA_GAUGES = (
    "memory/xla_argument_bytes",
    "memory/xla_output_bytes",
    "memory/xla_temp_bytes",
    "memory/xla_alias_bytes",
    "memory/xla_generated_code_bytes",
)
HBM_GAUGES = (
    "engine/hbm_peak_bytes",
    "memory/hbm_headroom_bytes",
    "memory/hbm_limit_bytes",
)


# ---------------------------------------------------------------------------
# Loading
# ---------------------------------------------------------------------------

def _host_of_metrics_path(path: str) -> str:
    """``metrics.jsonl`` -> "local"; ``metrics.<host>.jsonl`` -> host."""
    name = os.path.basename(path)
    parts = name.split(".")
    return parts[1] if len(parts) > 2 else "local"


def load_host_metrics(run_dir: str,
                      metrics_file: str = DEFAULT_METRICS_FILE) -> \
        Dict[str, Dict[str, float]]:
    """{host: {tag: latest value}} for the memory-relevant gauges, from
    plain and host-scoped metrics JSONL files. Torn trailing lines (a
    crash mid-append) are tolerated."""
    root, ext = os.path.splitext(metrics_file)
    paths = sorted(set(glob.glob(os.path.join(run_dir, metrics_file))
                       + glob.glob(os.path.join(run_dir,
                                                f"{root}.*{ext}"))))
    wanted = set(LEDGER_GAUGES) | set(XLA_GAUGES) | set(HBM_GAUGES)
    out: Dict[str, Dict[str, float]] = {}
    for path in paths:
        latest: Dict[str, float] = out.setdefault(
            _host_of_metrics_path(path), {})
        try:
            with open(path) as f:
                for line in f:
                    try:
                        row = json.loads(line)
                    except ValueError:
                        continue
                    tag = row.get("tag")
                    if tag in wanted and row.get("value") is not None:
                        latest[tag] = float(row["value"])
        except OSError:
            continue
    return out


def load_plans(run_dir: str) -> Dict[str, Dict[str, Any]]:
    """{host: plan} from ``memory_plan*.json`` (host-scoped or plain)."""
    out: Dict[str, Dict[str, Any]] = {}
    for path in sorted(glob.glob(os.path.join(run_dir,
                                              "memory_plan*.json"))):
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        name = os.path.basename(path)
        parts = name.split(".")
        host = parts[1] if len(parts) > 2 else "local"
        out[host] = doc
    return out


def load_crashdumps(dirs: List[str]) -> List[Dict[str, Any]]:
    """OOM crashdump summaries from every ``oom_step*/`` directory under
    the given dirs (each dir may BE a dump dir or contain them)."""
    dumps: List[Dict[str, Any]] = []
    candidates: List[str] = []
    for d in dirs:
        if not d or not os.path.isdir(d):
            continue
        if os.path.basename(d).startswith("oom_"):
            candidates.append(d)
        candidates.extend(sorted(glob.glob(os.path.join(d, "oom_*"))))
    seen = set()
    for path in candidates:
        real = os.path.realpath(path)
        if real in seen:
            continue
        seen.add(real)
        info_path = os.path.join(path, "info.json")
        if not os.path.isfile(info_path):
            continue
        try:
            with open(info_path) as f:
                info = json.load(f)
        except (OSError, ValueError):
            continue
        dump = {"path": path, "step": info.get("step"),
                "label": info.get("label"),
                "error": (info.get("error") or "").splitlines()[:1],
                "exit_code": info.get("exit_code"),
                "min_headroom_bytes": None,
                "ledger_device_bytes": None}
        try:
            with open(os.path.join(path, "memory.json")) as f:
                dump["min_headroom_bytes"] = json.load(f).get(
                    "min_headroom_bytes")
        except (OSError, ValueError):
            pass
        try:
            with open(os.path.join(path, "ledger.json")) as f:
                dump["ledger_device_bytes"] = (json.load(f)
                                               .get("per_device", {})
                                               .get("model_state_bytes"))
        except (OSError, ValueError):
            pass
        dumps.append(dump)
    return dumps


# ---------------------------------------------------------------------------
# Merge
# ---------------------------------------------------------------------------

def merge_memory(run_dir: str,
                 crashdump_dirs: Optional[List[str]] = None,
                 metrics_file: str = DEFAULT_METRICS_FILE) -> Dict[str, Any]:
    metrics = load_host_metrics(run_dir, metrics_file)
    plans = load_plans(run_dir)
    dump_dirs = list(crashdump_dirs or [])
    # The observatory's default crashdump dir is relative to the child's
    # cwd; also look beside/inside the run dir for convenience.
    dump_dirs += [run_dir, os.path.join(run_dir, "crashdumps")]
    dumps = load_crashdumps(dump_dirs)

    hosts = []
    for host in sorted(set(metrics) | set(plans)):
        m = metrics.get(host, {})
        row = {"host": host}
        for tag in LEDGER_GAUGES + XLA_GAUGES + HBM_GAUGES:
            row[tag.split("/")[-1]] = m.get(tag)
        hosts.append(row)
    tightest = None
    with_headroom = [h for h in hosts
                     if h.get("hbm_headroom_bytes") not in (None, 0)]
    if with_headroom:
        tightest = min(with_headroom,
                       key=lambda h: h["hbm_headroom_bytes"])["host"]
    return {
        "run_dir": os.path.abspath(run_dir),
        "n_hosts": len(hosts),
        "hosts": hosts,
        "tightest_host": tightest,
        "plans": plans,
        "crashdumps": dumps,
    }


# ---------------------------------------------------------------------------
# Rendering
# ---------------------------------------------------------------------------

def _gb(v: Optional[float], na: str = "n/a") -> str:
    return f"{v / 1024**3:.3f}" if v is not None else na


def render_plan(plan: Dict[str, Any]) -> str:
    """The what-if table, from the persisted plan JSON (the package-side
    twin is telemetry/memory.py render_plan_table)."""
    lines = [
        f"capacity plan: {plan.get('total_params', 0) / 1e6:.1f}M params, "
        f"{plan.get('num_shards', 1)} ZeRO shard(s), microbatch "
        f"{plan.get('microbatch', 1)}, HBM limit "
        f"{_gb(plan.get('hbm_limit_bytes'))} GB"]
    hdr = (f"  {'config':<20} {'model GB':>9} {'device GB':>10} "
           f"{'host GB':>8} {'headroom GB':>12}  verdict")
    lines.append(hdr)
    lines.append("  " + "-" * (len(hdr) - 2))
    for r in plan.get("rows", []):
        name = (f"stage{r['stage']}" + ("+offload" if r["offload"] else "")
                + (" *" if r.get("chosen") else ""))
        verdict = r.get("verdict", "unknown")
        lines.append(
            f"  {name:<20} {_gb(r.get('model_state_bytes')):>9} "
            f"{_gb(r.get('device_bytes')):>10} {_gb(r.get('host_bytes')):>8} "
            f"{_gb(r.get('headroom_bytes')):>12}  "
            f"{verdict.upper() if verdict == 'over' else verdict}")
    for m in plan.get("microbatch_projection", []):
        lines.append(f"  microbatch {m['microbatch']:<4} -> device "
                     f"{_gb(m.get('device_bytes'))} GB  {m.get('verdict')}")
    return "\n".join(lines)


def render(report: Dict[str, Any]) -> str:
    out = [f"memory report — {report['n_hosts']} host(s) "
           f"({report['run_dir']})"]
    if report["hosts"]:
        out.append("")
        hdr = (f"{'host':<14} {'master':>8} {'optim':>8} {'grads':>8} "
               f"{'compute':>8} {'ledger':>8} {'xla args':>9} "
               f"{'xla temp':>9} {'peak':>8} {'headroom':>9}   (GB)")
        out.append(hdr)
        out.append("-" * len(hdr))
        for h in report["hosts"]:
            out.append(
                f"{h['host']:<14} {_gb(h['ledger_master_bytes']):>8} "
                f"{_gb(h['ledger_optimizer_bytes']):>8} "
                f"{_gb(h['ledger_grads_bytes']):>8} "
                f"{_gb(h['ledger_compute_params_bytes']):>8} "
                f"{_gb(h['ledger_device_bytes']):>8} "
                f"{_gb(h['xla_argument_bytes']):>9} "
                f"{_gb(h['xla_temp_bytes']):>9} "
                f"{_gb(h['hbm_peak_bytes']):>8} "
                f"{_gb(h['hbm_headroom_bytes']):>9}")
    if report.get("tightest_host"):
        out.append("")
        out.append(f"tightest host (min headroom): "
                   f"{report['tightest_host']}")
    for host, plan in sorted(report.get("plans", {}).items()):
        out.append("")
        out.append(f"[{host}] " + render_plan(plan))
    if report.get("crashdumps"):
        out.append("")
        out.append("OOM crashdumps:")
        for d in report["crashdumps"]:
            err = d["error"][0] if d["error"] else ""
            out.append(
                f"  step {d.get('step')} ({d.get('label')}) rc="
                f"{d.get('exit_code')} headroom "
                f"{_gb(d.get('min_headroom_bytes'))} GB ledger "
                f"{_gb(d.get('ledger_device_bytes'))} GB — {err[:80]}")
            out.append(f"    at {d['path']}")
    return "\n".join(out)


# ---------------------------------------------------------------------------
# Selftest
# ---------------------------------------------------------------------------

def _write(path: str, doc: Any) -> None:
    with open(path, "w") as f:
        json.dump(doc, f)


def _selftest() -> int:
    """Synthesize a 2-host run dir (host-scoped metrics with ledger/XLA/
    headroom gauges, a plan with an over-HBM row, an OOM crashdump) and
    assert the merged report names the tightest host, renders the
    what-if verdicts, and surfaces the crashdump."""
    gb = 1024**3
    with tempfile.TemporaryDirectory() as td:
        for host, headroom in (("hostA", 4 * gb), ("hostB", 1 * gb)):
            rows = [
                {"tag": "memory/ledger_master_bytes", "value": 2 * gb,
                 "step": 0, "kind": "gauge"},
                {"tag": "memory/ledger_optimizer_bytes", "value": 4 * gb,
                 "step": 0, "kind": "gauge"},
                {"tag": "memory/ledger_grads_bytes", "value": 1 * gb,
                 "step": 0, "kind": "gauge"},
                {"tag": "memory/ledger_compute_params_bytes",
                 "value": 1 * gb, "step": 0, "kind": "gauge"},
                {"tag": "memory/ledger_device_bytes", "value": 8 * gb,
                 "step": 0, "kind": "gauge"},
                {"tag": "memory/xla_argument_bytes", "value": 8.2 * gb,
                 "step": 1, "kind": "gauge"},
                {"tag": "memory/xla_temp_bytes", "value": 2.5 * gb,
                 "step": 1, "kind": "gauge"},
                {"tag": "engine/hbm_peak_bytes", "value": 11 * gb,
                 "step": 1, "kind": "gauge"},
                {"tag": "memory/hbm_headroom_bytes", "value": headroom,
                 "step": 1, "kind": "gauge"},
                {"tag": "memory/hbm_limit_bytes", "value": 16 * gb,
                 "step": 1, "kind": "gauge"},
            ]
            with open(os.path.join(td, f"metrics.{host}.jsonl"), "w") as f:
                for r in rows:
                    f.write(json.dumps(r) + "\n")
                f.write('{"tag": "torn')          # must be tolerated
        _write(os.path.join(td, "memory_plan.hostA.json"), {
            "format": 1, "total_params": 1.3e9, "num_shards": 8,
            "microbatch": 8, "hbm_limit_bytes": 16 * gb,
            "rows": [
                {"stage": 0, "offload": False,
                 "model_state_bytes": 20 * gb, "device_bytes": 20 * gb,
                 "host_bytes": 0, "headroom_bytes": -4 * gb,
                 "verdict": "over", "chosen": True},
                {"stage": 2, "offload": False,
                 "model_state_bytes": 6 * gb, "device_bytes": 6 * gb,
                 "host_bytes": 0, "headroom_bytes": 10 * gb,
                 "verdict": "ok", "chosen": False},
            ],
            "microbatch_projection": []})
        dump = os.path.join(td, "crashdumps", "oom_step7_4711")
        os.makedirs(dump)
        _write(os.path.join(dump, "info.json"), {
            "kind": "oom", "step": 7, "label": "train_step",
            "pid": 4711, "exit_code": 114,
            "error": "RESOURCE_EXHAUSTED: Out of memory allocating "
                     "2147483648 bytes"})
        _write(os.path.join(dump, "memory.json"),
               {"devices": [], "min_headroom_bytes": int(0.1 * gb)})
        _write(os.path.join(dump, "ledger.json"),
               {"per_device": {"model_state_bytes": 8 * gb}})

        report = merge_memory(td)
        assert report["n_hosts"] == 2, report
        assert report["tightest_host"] == "hostB", report
        by_host = {h["host"]: h for h in report["hosts"]}
        assert by_host["hostA"]["ledger_device_bytes"] == 8 * gb
        assert by_host["hostB"]["hbm_headroom_bytes"] == 1 * gb
        assert len(report["crashdumps"]) == 1
        assert report["crashdumps"][0]["step"] == 7
        text = render(report)
        assert "hostB" in text and "tightest" in text
        assert "OVER" in text and "stage0 *" in text     # plan verdicts
        assert "OOM crashdumps" in text
        assert "RESOURCE_EXHAUSTED" in text
        json.dumps(report)                                # serializable
    print("selftest ok")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("run_dir", nargs="?",
                    help="the job's telemetry.dir (metrics JSONL + "
                         "memory_plan*.json live there)")
    ap.add_argument("--crashdumps", action="append", default=None,
                    metavar="DIR",
                    help="additional crashdump dir(s) to scan for "
                         "oom_step*/ dumps (repeatable); the run dir and "
                         "<run_dir>/crashdumps are always scanned")
    ap.add_argument("--metrics-file", default=DEFAULT_METRICS_FILE)
    ap.add_argument("--json", action="store_true",
                    help="machine-readable report")
    ap.add_argument("--selftest", action="store_true",
                    help="run the built-in round-trip check and exit")
    args = ap.parse_args(argv)
    if args.selftest:
        return _selftest()
    if not args.run_dir:
        ap.error("run_dir is required (or --selftest)")
    report = merge_memory(args.run_dir, crashdump_dirs=args.crashdumps,
                          metrics_file=args.metrics_file)
    if args.json:
        print(json.dumps(report, indent=1, sort_keys=True))
    else:
        print(render(report))
    return 0


if __name__ == "__main__":
    sys.exit(main())
