"""Acceptance probe: async checkpointing stays off the step path.

Times the same tiny-MLP training loop three ways — resilience disabled,
async checkpointing every step, and synchronous (inline-write) checkpointing
every step — and reports per-step wall clock. The async column must sit
within noise of disabled (the step only pays the host snapshot; serialize +
fsync happen on the writer thread), while the sync column shows the cost
the subsystem exists to avoid.

Run: JAX_PLATFORMS=cpu python tools/probe_resilience_overhead.py
"""

import json
import os
import shutil
import sys
import tempfile
import time

os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") + \
    " --xla_force_host_platform_device_count=8"
_ROOT = os.path.join(os.path.dirname(os.path.abspath(__file__)), os.pardir)
sys.path.insert(0, _ROOT)
sys.path.insert(0, os.path.join(_ROOT, "tests"))

import jax  # noqa: E402
import numpy as np  # noqa: E402

import deepspeed_tpu  # noqa: E402
from deepspeed_tpu.parallel.mesh import build_mesh  # noqa: E402
from simple_model import mlp_loss_fn, mlp_params, random_batches  # noqa: E402

STEPS = 30
WARMUP = 5
# Modest model: the step-path cost of an async save is ONE host snapshot
# (D2H), so it scales with state size; the cost async exists to hide —
# serialize + per-shard fsync + rename — is dominated by I/O latency and
# shows in the sync column at any size.
HIDDEN, LAYERS = 128, 2


def build(ckpt_dir=None, async_write=True):
    config = {
        "train_micro_batch_size_per_gpu": 2,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 1},
        "steps_per_print": 10_000,
    }
    if ckpt_dir is not None:
        config["resilience"] = {
            "enabled": True,
            "checkpoint": {"dir": ckpt_dir, "interval": 1, "keep_last": 2,
                           "async": async_write},
        }
    engine, _, _, _ = deepspeed_tpu.initialize(
        loss_fn=mlp_loss_fn, params=mlp_params(hidden=HIDDEN, layers=LAYERS),
        config=config, mesh=build_mesh(data=8), rng_seed=0)
    return engine


def time_steps(engine, batches):
    for b in batches[:WARMUP]:
        engine.train_batch(b)
    jax.block_until_ready(engine.state.params)
    times = []
    for b in batches[WARMUP:]:
        t0 = time.perf_counter()
        loss = engine.train_batch(b)
        jax.block_until_ready(loss)
        times.append(time.perf_counter() - t0)
    if engine.ckpt_manager is not None:
        engine.ckpt_manager.wait()
        engine.ckpt_manager.close()
    return times


def main():
    rng = np.random.default_rng(0)
    batches = [random_batches(rng, 1, batch_size=16, hidden=HIDDEN)
               for _ in range(STEPS)]
    root = tempfile.mkdtemp(prefix="resilience_probe_")
    rows = {}
    try:
        for name, kw in [("disabled", {"ckpt_dir": None}),
                         ("async", {"ckpt_dir": os.path.join(root, "a")}),
                         ("sync", {"ckpt_dir": os.path.join(root, "s"),
                                   "async_write": False})]:
            times = time_steps(build(**kw), batches)
            rows[name] = {"median_ms": round(1e3 * float(np.median(times)), 3),
                          "p90_ms": round(1e3 * float(np.quantile(times, 0.9)), 3)}
    finally:
        shutil.rmtree(root, ignore_errors=True)

    base, async_, sync = (rows[k]["median_ms"]
                          for k in ("disabled", "async", "sync"))
    rows["async_overhead_x"] = round(async_ / base, 3)
    rows["sync_overhead_x"] = round(sync / base, 3)
    # "Within noise": the async step path pays only the host snapshot.
    rows["off_step_path"] = bool(async_ <= base * 1.5 + 2.0)
    print(json.dumps(rows, indent=1))
    return 0 if rows["off_step_path"] else 1


if __name__ == "__main__":
    sys.exit(main())
