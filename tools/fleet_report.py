#!/usr/bin/env python
"""Merge a multi-host run's telemetry into ONE fleet report + timeline.

The fleet-level artifact (docs/OBSERVABILITY.md "Fleet observability"):
feed it the job's ``telemetry.dir`` — where each host wrote its
``metrics.<host>.jsonl`` / ``trace.<host>.json`` (single-host runs keep
the bare names), the goodput ``run_manifest.aNNNN.<host>.json`` files,
and host 0's ``fleet_breakdown.json`` — and get:

- a **fleet summary table**: per-host goodput %, MFU, steps, mean step
  time, exposed-comm fraction, and the straggler verdict (count +
  persistent flag from the fleet detector's rolling z-score);
- a **clock-aligned merged Perfetto timeline** (``--timeline OUT.json``):
  every host's Chrome-trace spans on one time axis, aligned via the
  ``wall_epoch`` anchor each tracer stamps in its metadata, one process
  row per host;
- optionally (``--profile-dir``) **measured collective time** parsed out
  of ``jax.profiler`` perfetto captures (``*.trace.json.gz``) — the
  ground-truth check on the modeled ``comm/exposed_frac``.

Standalone on purpose: stdlib only (json, gzip, glob), so it runs
anywhere the run dir lands — including hosts without jax.

Usage:
    python tools/fleet_report.py RUN_DIR [--json] [--timeline OUT.json]
                                 [--profile-dir DIR]
    python tools/fleet_report.py --selftest
"""

import argparse
import glob
import importlib.util
import json
import os
import sys
import tempfile
from typing import Any, Dict, List, Optional


def _load_traceparse():
    """Load telemetry/traceparse.py by path (stdlib-only module): ONE
    capture parser in the tree, and this tool stays runnable on hosts
    where the package (and jax) cannot import."""
    cached = sys.modules.get("dstpu_traceparse")
    if cached is not None:
        return cached
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    path = os.path.join(root, "deepspeed_tpu", "telemetry", "traceparse.py")
    spec = importlib.util.spec_from_file_location("dstpu_traceparse", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    # One instance per process: a tool importing another tool (or tests
    # loading several) must see the same COLLECTIVE_RE/CATEGORIES objects.
    sys.modules["dstpu_traceparse"] = mod
    return mod


_tp = _load_traceparse()

MANIFEST_PREFIX = "run_manifest."
BREAKDOWN_GLOB = "fleet_breakdown*.json"
# THE collective-op-name list + the capture scan both live in traceparse
# now; re-bound here so the historical names keep working.
COLLECTIVE_RE = _tp.COLLECTIVE_RE
scan_profile_dir = _tp.scan_profile_dir

# Metric tags the merge consumes (last value per (host, tag) wins — the
# gauges are cumulative).
_TAGS_OF_INTEREST = ("comm/exposed_frac", "engine/mfu",
                     "goodput/goodput_frac", "goodput/productive_step_sec",
                     "goodput/wall_sec", "goodput/steps_committed",
                     "goodput/exposed_comm_sec", "goodput/straggler_sec")


# ---------------------------------------------------------------------------
# Discovery / loading
# ---------------------------------------------------------------------------

def _host_from_filename(name: str, stem: str, ext: str) -> Optional[str]:
    """'metrics.hostA.jsonl' -> 'hostA'; bare 'metrics.jsonl' -> None."""
    if not (name.startswith(stem + ".") and name.endswith(ext)):
        return None
    middle = name[len(stem) + 1:-len(ext)]
    return middle.rstrip(".") or None


def discover(run_dir: str) -> Dict[str, Any]:
    names = sorted(os.listdir(run_dir))
    metrics, traces = {}, {}
    for n in names:
        if n == "metrics.jsonl":
            metrics[None] = os.path.join(run_dir, n)
        else:
            h = _host_from_filename(n, "metrics", ".jsonl")
            if h:
                metrics[h] = os.path.join(run_dir, n)
        if n == "trace.json":
            traces[None] = os.path.join(run_dir, n)
        else:
            h = _host_from_filename(n, "trace", ".json")
            if h and not n.endswith(".tmp"):
                traces[h] = os.path.join(run_dir, n)
    manifests = []
    for n in names:
        if n.startswith(MANIFEST_PREFIX) and n.endswith(".json"):
            try:
                with open(os.path.join(run_dir, n)) as f:
                    manifests.append(json.load(f))
            except (OSError, ValueError):
                continue
    breakdown = None
    for p in sorted(glob.glob(os.path.join(run_dir, BREAKDOWN_GLOB))):
        try:
            with open(p) as f:
                breakdown = json.load(f)
        except (OSError, ValueError):
            continue
    return {"metrics": metrics, "traces": traces, "manifests": manifests,
            "breakdown": breakdown}


def load_metrics_last(path: str) -> Dict[str, float]:
    """Last value per interesting tag in one metrics JSONL (torn final
    lines of killed attempts tolerated)."""
    out: Dict[str, float] = {}
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    row = json.loads(line)
                except ValueError:
                    continue
                tag = row.get("tag", "")
                if tag in _TAGS_OF_INTEREST or tag.startswith("fleet/"):
                    out[tag] = float(row.get("value", 0.0))
    except OSError:
        pass
    return out


def load_trace(path: str) -> Dict[str, Any]:
    with open(path) as f:
        doc = json.load(f)
    if isinstance(doc, list):           # bare-array Chrome trace variant
        doc = {"traceEvents": doc, "metadata": {}}
    doc.setdefault("metadata", {})
    return doc


# ---------------------------------------------------------------------------
# Merge
# ---------------------------------------------------------------------------

def merge_fleet(run_dir: str) -> Dict[str, Any]:
    found = discover(run_dir)
    manifests = found["manifests"]
    breakdown = found["breakdown"]
    metrics = {h: load_metrics_last(p) for h, p in found["metrics"].items()}
    hosts: List[str] = []

    def _add(h):
        if h and h not in hosts:
            hosts.append(h)

    for m in manifests:
        _add(m.get("host"))
    for h in found["metrics"]:
        _add(h)
    if breakdown:
        for h in breakdown.get("hosts", []):
            _add(h)
    if not hosts:
        hosts = ["local"]

    # The bare metrics.jsonl / trace.json belong to the run's only host
    # when there is exactly one (the single-host compat alias).
    def _metrics_for(host):
        if host in metrics:
            return metrics[host]
        if None in metrics and len(hosts) == 1:
            return metrics[None]
        return {}

    straggler_info = (breakdown or {}).get("stragglers", {})
    bd_hosts = (breakdown or {}).get("hosts", [])
    bd_fields = (breakdown or {}).get("fields", {})

    rows = []
    for host in hosts:
        mrows = [m for m in manifests if m.get("host") == host]
        mt = _metrics_for(host)
        wall = sum(float(m.get("wall_sec") or 0.0) for m in mrows)
        productive = sum(
            float((m.get("categories") or {}).get("productive_step", 0.0))
            for m in mrows)
        if wall <= 0:
            wall = mt.get("goodput/wall_sec", 0.0)
            productive = mt.get("goodput/productive_step_sec", productive)
        weights = [(float((m.get("categories") or {})
                          .get("productive_step", 0.0)), m.get("mfu"))
                   for m in mrows if m.get("mfu") is not None]
        wsum = sum(w for w, _ in weights)
        mfu = (sum(w * f for w, f in weights) / wsum if wsum > 0
               else (weights[-1][1] if weights
                     else mt.get("engine/mfu")))
        steps = max((int(m.get("steps_committed") or 0) for m in mrows),
                    default=int(mt.get("goodput/steps_committed", 0)))
        step_time = None
        if host in bd_hosts and "step_time_sec" in bd_fields:
            step_time = bd_fields["step_time_sec"][bd_hosts.index(host)]
        elif mrows:
            sts = [m.get("mean_step_time_sec") for m in mrows
                   if m.get("mean_step_time_sec") is not None]
            step_time = sum(sts) / len(sts) if sts else None
        s = straggler_info.get(host) or {}
        rows.append({
            "host": host,
            "steps_committed": steps,
            "wall_sec": wall,
            "goodput_frac": (productive / wall) if wall > 0
            else mt.get("goodput/goodput_frac"),
            "mfu": mfu,
            "mean_step_time_sec": step_time,
            "exposed_frac": mt.get("comm/exposed_frac"),
            "exposed_comm_sec": mt.get("goodput/exposed_comm_sec"),
            "straggler": bool(s),
            "straggler_count": int(s.get("count", 0)),
            "straggler_persistent": bool(s.get("persistent", False)),
            "straggler_zscore": s.get("last_zscore"),
        })

    stragglers = sorted(h for h, s in straggler_info.items())
    persistent = sorted(h for h, s in straggler_info.items()
                        if s.get("persistent"))
    # Eviction decisions (resilience/elastic.py cost model): union over
    # every attempt's manifests — the engine's in-process decisions plus
    # the supervisor's post-mortem stamps — deduplicated.
    evictions: List[Dict[str, Any]] = []
    seen_ev = set()
    for m in manifests:
        for d in (m.get("eviction_decisions") or []):
            key = (d.get("host"), d.get("step"), d.get("source"))
            if key not in seen_ev:
                seen_ev.add(key)
                evictions.append(d)
    return {
        "run_dir": os.path.abspath(run_dir),
        "hosts": rows,
        "n_hosts": len(rows),
        "fleet_stats": (breakdown or {}).get("stats"),
        "stragglers": stragglers,
        "persistent_stragglers": persistent,
        "eviction_decisions": evictions,
        "breakdown_step": (breakdown or {}).get("step"),
        "trace_files": {h or "local": p
                        for h, p in found["traces"].items()},
    }


def merge_timeline(trace_paths: Dict[Optional[str], str]) -> Dict[str, Any]:
    """One clock-aligned Perfetto document from per-host traces: each
    host's events shift onto a common time axis via the ``wall_epoch``
    anchor its tracer stamped, and land in their own process row (pid =
    host index, named by a process_name metadata event)."""
    docs = []
    for host, path in sorted(trace_paths.items(),
                             key=lambda kv: kv[0] or ""):
        doc = load_trace(path)
        meta = doc.get("metadata") or {}
        label = meta.get("host") or host or \
            os.path.splitext(os.path.basename(path))[0]
        wall = meta.get("wall_epoch")
        docs.append((label, float(wall) if wall else None,
                     doc.get("traceEvents", [])))
    if not docs:
        return {"traceEvents": [], "displayTimeUnit": "ms"}
    # Anchorless traces (pre-fleet files, bare-array variants) stay
    # base-aligned instead of poisoning the base with epoch 0 — which
    # would shift every anchored host by ~the unix epoch.
    anchors = [w for _, w, _ in docs if w is not None]
    base = min(anchors) if anchors else 0.0
    merged: List[Dict[str, Any]] = []
    for pid, (label, wall, events) in enumerate(docs):
        shift_us = ((wall - base) * 1e6) if wall is not None else 0.0
        merged.append({"name": "process_name", "ph": "M", "pid": pid,
                       "tid": 0, "args": {"name": label}})
        for ev in events:
            if ev.get("ph") == "M" and ev.get("name") == "process_name":
                continue                 # replaced by the host-named row
            ev = dict(ev)
            ev["pid"] = pid
            if "ts" in ev:
                ev["ts"] = float(ev["ts"]) + shift_us
            merged.append(ev)
    return {"traceEvents": merged, "displayTimeUnit": "ms",
            "metadata": {"aligned_to_wall_epoch": base if anchors else None,
                         "hosts": [l for l, _, _ in docs]}}


# ---------------------------------------------------------------------------
# Rendering
# ---------------------------------------------------------------------------

def _fmt(v, spec, na="n/a"):
    return format(v, spec) if v is not None else na


def render(report: Dict[str, Any]) -> str:
    out = [f"fleet report — {report['n_hosts']} host(s) "
           f"({report['run_dir']})"]
    out.append("")
    hdr = (f"{'host':<16} {'steps':>6} {'wall s':>9} {'goodput':>8} "
           f"{'mfu':>7} {'step s':>8} {'exposed':>8} {'straggler':>16}")
    out.append(hdr)
    out.append("-" * len(hdr))
    for r in report["hosts"]:
        if r["straggler"]:
            verdict = (f"YES x{r['straggler_count']}"
                       + (" (persistent)" if r["straggler_persistent"]
                          else ""))
        else:
            verdict = "no"
        out.append(
            f"{r['host']:<16} {r['steps_committed']:>6} "
            f"{r['wall_sec']:>9.1f} "
            f"{_fmt(r['goodput_frac'], '.1%'):>8} "
            f"{_fmt(r['mfu'], '.1%'):>7} "
            f"{_fmt(r['mean_step_time_sec'], '.3f'):>8} "
            f"{_fmt(r['exposed_frac'], '.1%'):>8} {verdict:>16}")
    stats = report.get("fleet_stats")
    if stats:
        out.append("")
        out.append(f"fleet spread (flush @ step {report['breakdown_step']}):")
        for field, s in stats.items():
            line = (
                f"  {field:<20} min {s['min']:>12.4g}  "
                f"median {s['median']:>12.4g}  max {s['max']:>12.4g}  "
                f"argmax {s.get('argmax_host_name', s['argmax_host'])}")
            if "argmin_host" in s:
                # names the tightest host for the headroom field
                line += (f"  argmin "
                         f"{s.get('argmin_host_name', s['argmin_host'])}")
            out.append(line)
    if report.get("persistent_stragglers"):
        out.append("")
        out.append("persistent straggler(s): "
                   + ", ".join(report["persistent_stragglers"]))
    if report.get("eviction_decisions"):
        out.append("")
        out.append("eviction decisions (goodput cost model, "
                   "resilience/elastic.py):")
        for d in report["eviction_decisions"]:
            out.append(
                f"  [{d.get('source', 'engine')}] host={d.get('host')} "
                f"z={d.get('zscore')} "
                f"gain={float(d.get('projected_gain_sec') or 0.0):.1f}s "
                f"cost={float(d.get('reshard_cost_sec') or 0.0):.1f}s "
                f"(x{d.get('min_gain_factor')}) -> "
                f"{'EVICT' if d.get('evict') else 'keep'}")
    profile = report.get("profile")
    if profile:
        out.append("")
        out.append("measured collectives (jax.profiler captures):")
        for name, p in profile.items():
            out.append(f"  {name}: {p['collective_ms']:.1f} ms collective "
                       f"of {p['total_ms']:.1f} ms device "
                       f"({p['collective_frac']:.1%})")
    return "\n".join(out)


# ---------------------------------------------------------------------------
# Selftest
# ---------------------------------------------------------------------------

def _write(path: str, doc: Any) -> None:
    with open(path, "w") as f:
        json.dump(doc, f)


def _selftest() -> int:
    """Synthesize a 2-host run dir (manifests + host-scoped metrics +
    per-host traces with offset wall anchors + a breakdown naming hostB a
    persistent straggler), merge, and assert the properties the report is
    trusted for: the straggler verdict names the right host, per-host
    goodput/MFU/exposed-frac carry through, and the merged timeline is
    clock-aligned (hostB's spans shift by its wall-anchor offset)."""
    with tempfile.TemporaryDirectory() as td:
        for host, mfu, prod in (("hostA", 0.30, 40.0), ("hostB", 0.28, 38.0)):
            _write(os.path.join(td, f"run_manifest.a0000.{host}.json"), {
                "format": 1, "run_id": "cafe01", "attempt": 0, "host": host,
                "start_wall": 1000.0, "end_wall": 1062.0, "wall_sec": 62.0,
                "exit_rc": 0, "restart_cause": "clean",
                "categories": {"productive_step": prod, "data_stall": 4.0,
                               "recompile": 8.0, "init_restore": 5.0},
                "aux": {"exposed_comm_sec": 6.0},
                # Supervisor-stamped eviction decision (identical on every
                # host manifest — the report must dedup it to one row).
                "eviction_decisions": [
                    {"host": "hostB", "zscore": 4.2, "evict": True,
                     "projected_gain_sec": 300.0, "reshard_cost_sec": 60.0,
                     "min_gain_factor": 2.0, "step": None,
                     "source": "supervisor"}],
                "first_step": 1, "steps_committed": 30,
                "mean_step_time_sec": prod / 30, "mfu": mfu, "n_chips": 4})
        for host, frac in (("hostA", 0.12), ("hostB", 0.15)):
            with open(os.path.join(td, f"metrics.{host}.jsonl"), "w") as f:
                f.write(json.dumps({"tag": "comm/exposed_frac",
                                    "value": frac, "step": 30,
                                    "kind": "gauge"}) + "\n")
                f.write(json.dumps({"tag": "engine/mfu", "value": 0.30,
                                    "step": 30, "kind": "gauge"}) + "\n")
                f.write('{"tag": "torn')          # must be tolerated
        _write(os.path.join(td, "fleet_breakdown.json"), {
            "format": 1, "step": 30, "hosts": ["hostA", "hostB"],
            "fields": {"step_time_sec": [1.0, 1.5]},
            "stats": {"step_time_sec": {
                "min": 1.0, "median": 1.25, "max": 1.5,
                "argmax_host": 1, "argmax_host_name": "hostB"}},
            "stragglers": {"hostB": {"count": 3, "persistent": True,
                                     "last_zscore": 4.2}},
            "window": 8, "zscore_threshold": 3.0})
        for host, wall_epoch in (("hostA", 1000.0), ("hostB", 1005.0)):
            _write(os.path.join(td, f"trace.{host}.json"), {
                "traceEvents": [
                    {"name": "train_step", "ph": "X", "pid": 1, "tid": 1,
                     "ts": 0.0, "dur": 1.0e6},
                ],
                "displayTimeUnit": "ms",
                "metadata": {"wall_epoch": wall_epoch, "host": host}})

        report = merge_fleet(td)
        report["profile"] = {}
        text = render(report)
        timeline = merge_timeline(
            {h: p for h, p in report["trace_files"].items()})

    assert report["n_hosts"] == 2, report["hosts"]
    by_host = {r["host"]: r for r in report["hosts"]}
    # straggler verdict names the right host — and only it
    assert by_host["hostB"]["straggler"] and \
        by_host["hostB"]["straggler_persistent"]
    assert not by_host["hostA"]["straggler"]
    assert report["persistent_stragglers"] == ["hostB"]
    # goodput / mfu / exposed carried through per host
    assert abs(by_host["hostA"]["goodput_frac"] - 40.0 / 62.0) < 1e-9
    assert abs(by_host["hostB"]["mfu"] - 0.28) < 1e-9
    assert abs(by_host["hostB"]["exposed_frac"] - 0.15) < 1e-9
    # breakdown step times preferred over manifest means
    assert by_host["hostB"]["mean_step_time_sec"] == 1.5
    # merged timeline: clock-aligned — hostB's span shifted by +5 s
    spans = [e for e in timeline["traceEvents"] if e.get("ph") == "X"]
    by_pid = {e["pid"]: e for e in spans}
    assert abs(by_pid[0]["ts"] - 0.0) < 1e-6
    assert abs(by_pid[1]["ts"] - 5.0e6) < 1e-6
    names = {e["args"]["name"] for e in timeline["traceEvents"]
             if e.get("ph") == "M" and e.get("name") == "process_name"}
    assert {"hostA", "hostB"} <= names
    assert "hostB" in text and "persistent" in text
    # eviction decisions: deduped to one row (both host manifests carried
    # the same supervisor stamp) and rendered with the evidence
    assert len(report["eviction_decisions"]) == 1
    assert "eviction decisions" in text and "EVICT" in text
    print(text)
    print("\nselftest ok")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("run_dir", nargs="?",
                    help="the job's telemetry.dir (per-host metrics/"
                         "traces, run manifests, fleet breakdown)")
    ap.add_argument("--json", action="store_true",
                    help="emit the merged report as JSON")
    ap.add_argument("--timeline", metavar="OUT",
                    help="also write the clock-aligned merged Perfetto "
                         "trace to OUT")
    ap.add_argument("--profile-dir",
                    help="jax.profiler dir: parse *.trace.json.gz "
                         "captures for measured collective time")
    ap.add_argument("--selftest", action="store_true",
                    help="run the built-in 2-host round-trip check")
    args = ap.parse_args(argv)
    if args.selftest:
        return _selftest()
    if not args.run_dir:
        ap.error("run dir required (or --selftest)")
    report = merge_fleet(args.run_dir)
    if args.profile_dir:
        report["profile"] = scan_profile_dir(args.profile_dir)
    if args.timeline:
        timeline = merge_timeline(
            {h: p for h, p in report["trace_files"].items()})
        with open(args.timeline, "w") as f:
            json.dump(timeline, f)
        print(f"[fleet_report] merged timeline -> {args.timeline} "
              f"({len(timeline['traceEvents'])} events)", file=sys.stderr)
    if args.json:
        print(json.dumps(report, indent=1))
    else:
        print(render(report))
    return 0


if __name__ == "__main__":
    sys.exit(main())
