"""Acceptance probe: production-scale MoE (ISSUE 16 — all-to-all expert
dispatch, moe/* observability, MoE GPT workload).

Builds an 8-device virtual mesh (data=4 x expert=2 on CPU) and trains the
SAME tiny MoE GPT (4 experts, every 2nd block) through the engine under
each dispatch mode — the GShard one-hot ``einsum`` oracle, the
slot-``scatter`` path, and the explicit manual-region ``alltoall``
exchange (moe/dispatch.py) — gating on:

- every mode trains (finite, decreasing loss on one fixed batch — the
  memorization gate; fresh random batches hover at ln(vocab));
- the three modes agree: same routing semantics, so the fixed-seed loss
  trajectories must match to fp roundoff (the oracle-parity gate,
  end-to-end through the engine);
- the ``moe/load_balance_loss`` gauge emits and improves over training
  (min over the trajectory below the first flush);
- ``moe/dispatch_bytes_ici`` is nonzero exactly on the alltoall mode
  (the only mode whose wire is modeled, not inferred);
- an INJECTED imbalance — router kernels poisoned so every token picks
  expert 0 — makes the ``moe/capacity_overflow_frac`` gauge fire well
  above the balanced run's value (the overflow alarm a capacity-starved
  production run needs).

Run: JAX_PLATFORMS=cpu python tools/probe_moe.py [--selftest]
(--selftest shrinks the trajectory; same assertions).
"""

import argparse
import json
import os
import shutil
import sys
import tempfile

os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") + \
    " --xla_force_host_platform_device_count=8"
_ROOT = os.path.join(os.path.dirname(os.path.abspath(__file__)), os.pardir)
sys.path.insert(0, _ROOT)

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

import deepspeed_tpu  # noqa: E402
from deepspeed_tpu.parallel.mesh import build_mesh  # noqa: E402
from deepspeed_tpu.telemetry.registry import InMemorySink  # noqa: E402

SEQ = 16
EXPERTS = 4
MODES = ("einsum", "scatter", "alltoall")


def make_model_and_params():
    from deepspeed_tpu.models import make_gpt

    model, cfg = make_gpt("tiny", vocab_size=256, max_seq_len=SEQ,
                          hidden_size=32, num_heads=4, num_layers=2,
                          dropout_rate=0.0, dtype=jnp.float32,
                          moe_experts=EXPERTS, moe_k=1, moe_layer_freq=2)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, cfg.vocab_size, (8, SEQ), dtype=np.int32)
    params = model.init({"params": jax.random.PRNGKey(0),
                         "dropout": jax.random.PRNGKey(1)},
                        {"input_ids": ids})["params"]
    return model, cfg, params


def build_engine(dispatch, params, model, tdir):
    config = {
        "train_micro_batch_size_per_gpu": 2,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 1},
        "moe": {"enabled": True, "num_experts": EXPERTS, "k": 1,
                "layer_freq": 2, "capacity_factor": 1.25,
                "dispatch": dispatch},
        "telemetry": {"enabled": True, "dir": tdir},
        "steps_per_print": 1,
    }
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, params=jax.tree_util.tree_map(jnp.copy, params),
        mesh=build_mesh(data=4, expert=2), config=config)
    sink = engine.telemetry.registry.add_sink(InMemorySink())
    return engine, sink


def gauge_series(sink, tag):
    return [r["value"] for r in sink.rows if r["tag"] == tag]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--selftest", action="store_true",
                    help="short trajectory, same assertions")
    ap.add_argument("--steps", type=int, default=8)
    args = ap.parse_args()
    steps = 4 if args.selftest else args.steps

    tdir = tempfile.mkdtemp(prefix="probe_moe_")
    import atexit
    atexit.register(shutil.rmtree, tdir, ignore_errors=True)

    model, cfg, params = make_model_and_params()
    rng = np.random.default_rng(1)
    # One fixed batch, trained repeatedly (memorization gate).
    ids = rng.integers(0, cfg.vocab_size, (1, 8, SEQ), dtype=np.int32)

    losses, sinks = {}, {}
    for mode in MODES:
        engine, sink = build_engine(mode, params, model, tdir)
        sinks[mode] = sink
        losses[mode] = [float(engine.train_batch({"input_ids": ids.copy()}))
                        for _ in range(steps)]
        del engine

    print(f"{'mode':>9} {'first loss':>11} {'final loss':>11} "
          f"{'lb loss':>8} {'overflow':>9} {'wire B/step':>12}")
    rows = {}
    for mode in MODES:
        lb = gauge_series(sinks[mode], "moe/load_balance_loss")
        ov = gauge_series(sinks[mode], "moe/capacity_overflow_frac")
        wire = gauge_series(sinks[mode], "moe/dispatch_bytes_ici")
        rows[mode] = {"losses": losses[mode], "load_balance": lb,
                      "overflow": ov, "wire": wire}
        print(f"{mode:>9} {losses[mode][0]:>11.4f} "
              f"{losses[mode][-1]:>11.4f} "
              f"{(lb[-1] if lb else float('nan')):>8.4f} "
              f"{(ov[-1] if ov else float('nan')):>9.4f} "
              f"{(wire[-1] if wire else 0):>12,.0f}")

    ok = True
    for mode in MODES:
        ls = losses[mode]
        if not np.isfinite(ls).all():
            print(f"FAIL: {mode} non-finite losses {ls}")
            ok = False
        elif ls[-1] >= ls[0]:
            print(f"FAIL: {mode} loss not decreasing "
                  f"{ls[0]:.4f} -> {ls[-1]:.4f}")
            ok = False

    # Oracle parity, end-to-end: same params/batch/routing => the three
    # dispatch modes must produce the same trajectory to fp roundoff.
    drift = max(
        float(np.max(np.abs(np.array(losses[m]) -
                            np.array(losses["einsum"]))))
        for m in ("scatter", "alltoall"))
    if drift > 1e-4:
        print(f"FAIL: dispatch modes diverge from the einsum oracle by "
              f"{drift:.2e} (> 1e-4)")
        ok = False

    for mode in MODES:
        lb = rows[mode]["load_balance"]
        if not lb:
            print(f"FAIL: {mode} moe/load_balance_loss never emitted")
            ok = False
        elif min(lb) >= lb[0] and len(lb) > 1 and lb[-1] >= lb[0]:
            print(f"FAIL: {mode} load-balance loss never improved "
                  f"({lb[0]:.4f} -> min {min(lb):.4f})")
            ok = False

    a2a_wire = rows["alltoall"]["wire"]
    if not a2a_wire or a2a_wire[-1] <= 0:
        print("FAIL: alltoall moe/dispatch_bytes_ici not positive")
        ok = False
    for mode in ("einsum", "scatter"):
        w = rows[mode]["wire"]
        if w and max(w) != 0:
            print(f"FAIL: {mode} models wire bytes {max(w)} (implicit "
                  f"reshard modes must report 0)")
            ok = False

    # Injected imbalance: poison the router kernels so every token picks
    # expert 0 — the overflow gauge must fire far above the balanced run.
    poisoned = jax.tree_util.tree_map(jnp.copy, params)
    for blk in poisoned:
        if isinstance(poisoned[blk], dict) and "moe" in poisoned[blk]:
            k = np.zeros(poisoned[blk]["moe"]["router"]["kernel"].shape,
                         np.float32)
            k[:, 0] = 10.0
            poisoned[blk]["moe"]["router"]["kernel"] = jnp.asarray(k)
    engine, sink = build_engine("scatter", poisoned, model, tdir)
    engine.train_batch({"input_ids": ids.copy()})
    ov = gauge_series(sink, "moe/capacity_overflow_frac")
    balanced_ov = (rows["scatter"]["overflow"] or [0.0])[-1]
    # The bias-free router maps the poison onto <=2 hot experts (sign of
    # the feature sum picks 0 or the tie-break), which at capacity_factor
    # 1.25 keeps at most 2*1.25/4 of tokens: overflow >= 0.375. Anything
    # above 0.3 — triple the balanced run — is an unambiguous alarm.
    if not ov or ov[-1] < 0.3:
        print(f"FAIL: injected imbalance overflow gauge {ov} did not fire")
        ok = False
    elif ov[-1] <= 2 * balanced_ov:
        print(f"FAIL: imbalanced overflow {ov[-1]:.3f} <= balanced "
              f"{balanced_ov:.3f}")
        ok = False

    print(json.dumps({
        "mesh": "data4 x expert2 (virtual, CPU)",
        "steps": steps,
        "experts": EXPERTS,
        "final_loss": {m: round(losses[m][-1], 5) for m in MODES},
        "oracle_max_drift": float(drift),
        "load_balance_last": {m: (rows[m]["load_balance"][-1]
                                  if rows[m]["load_balance"] else None)
                              for m in MODES},
        "alltoall_wire_bytes": (a2a_wire[-1] if a2a_wire else 0),
        "imbalance_overflow_frac": (round(ov[-1], 4) if ov else None),
        "pass": ok,
    }))
    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    main()
