"""Acceptance probe: guardrails cost nothing when off, <5% when on.

Times the 2-layer GPT training loop two ways — guardrails disabled and
guardrails enabled (detector + grad-norm tracking + rollback ring with a
snapshot every 5 steps) — and reports per-step wall clock. The disabled
column must sit within noise of the pre-guardrails engine (the hook is one
``is None`` check); the enabled column's budget is <5%: two scalar host
fetches per step plus the amortised ring snapshot.

Also measures the numerics observatory the same way (telemetry-on
baseline vs telemetry + numerics, same noise-floored <5% gate) — the
"in-program stats, single flush-boundary fetch" claim is measured here,
not asserted — and exercises the watchdog contract end to end: a
subprocess with a FaultPlan-injected hang must die with the distinct
watchdog rc and leave a crashdump containing thread stacks.

Run: JAX_PLATFORMS=cpu python tools/probe_guardrails.py [--selftest]
(--selftest shrinks the loop for CI; same assertions, looser gate).
"""

import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile
import textwrap
import time

os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") + \
    " --xla_force_host_platform_device_count=8"
_ROOT = os.path.join(os.path.dirname(os.path.abspath(__file__)), os.pardir)
sys.path.insert(0, _ROOT)
sys.path.insert(0, os.path.join(_ROOT, "tests"))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

import deepspeed_tpu  # noqa: E402
from deepspeed_tpu.config.constants import \
    GUARDRAILS_WATCHDOG_EXIT_CODE_DEFAULT  # noqa: E402
from deepspeed_tpu.parallel.mesh import build_mesh  # noqa: E402

SEQ = 16


def build_gpt_engine(num_layers=2, guardrails=False, numerics=None,
                     telemetry_dir=None):
    from deepspeed_tpu.models import make_gpt

    model, cfg = make_gpt("tiny", num_layers=num_layers, dropout_rate=0.0,
                          dtype=jnp.float32)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, cfg.vocab_size, (8, SEQ), dtype=np.int32)
    params = model.init({"params": jax.random.PRNGKey(0),
                         "dropout": jax.random.PRNGKey(1)},
                        {"input_ids": ids})["params"]
    config = {
        "train_micro_batch_size_per_gpu": 1,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "steps_per_print": 10_000,
    }
    if guardrails:
        config["guardrails"] = {
            "enabled": True,
            "detector": {"warmup_steps": 2, "zscore_threshold": 50.0},
            "rollback": {"snapshot_interval": 5, "ring_size": 2},
        }
    if numerics is not None:
        # Both columns run with telemetry ON (memory sink, no trace I/O)
        # so the measured delta is the numerics observatory alone — the
        # in-program stat reductions plus zero per-step host fetches
        # (the flush fetch sits outside the timed window:
        # steps_per_print=10_000).
        config["telemetry"] = {
            "enabled": True, "dir": telemetry_dir or ".",
            "trace": {"enabled": False},
            "metrics": {"sinks": ["memory"]},
            "numerics": {"enabled": bool(numerics)},
        }
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, params=params, mesh=build_mesh(data=8), config=config)
    return engine, cfg


def time_steps(engine, batches, warmup):
    for b in batches[:warmup]:
        engine.train_batch(b)
    jax.block_until_ready(engine.state.params)
    times = []
    for b in batches[warmup:]:
        t0 = time.perf_counter()
        loss = engine.train_batch(b)
        jax.block_until_ready(loss)
        times.append(time.perf_counter() - t0)
    return times


def probe_overhead(steps, warmup):
    rng = np.random.default_rng(1)
    rows = {}
    for name, on in [("off", False), ("on", True)]:
        engine, cfg = build_gpt_engine(guardrails=on)
        batches = [{"input_ids": rng.integers(
            0, cfg.vocab_size, (1, 8, SEQ), dtype=np.int32)}
            for _ in range(steps)]
        times = time_steps(engine, batches, warmup)
        rows[name] = {
            "median_ms": round(1e3 * float(np.median(times)), 3),
            "p90_ms": round(1e3 * float(np.quantile(times, 0.9)), 3)}
        if on:
            rows[name]["snapshots"] = engine.guardrails.ring.pushes
            rows[name]["verdicts"] = dict(engine.guardrails.detector.stats)
    rows["enabled_overhead_x"] = round(
        rows["on"]["median_ms"] / rows["off"]["median_ms"], 3)
    return rows


def probe_numerics(steps, warmup, telemetry_dir):
    """Numerics observatory overhead: telemetry-on baseline vs telemetry
    + numerics, same loop — the measured backing for the "in-program
    stats, single flush-boundary fetch" claim (the numerics flush never
    fires inside the timed window, so any delta is the in-program stat
    reductions alone)."""
    rng = np.random.default_rng(2)
    rows = {}
    for name, on in [("off", False), ("on", True)]:
        engine, cfg = build_gpt_engine(numerics=on,
                                       telemetry_dir=telemetry_dir)
        batches = [{"input_ids": rng.integers(
            0, cfg.vocab_size, (1, 8, SEQ), dtype=np.int32)}
            for _ in range(steps)]
        times = time_steps(engine, batches, warmup)
        rows[name] = {
            "median_ms": round(1e3 * float(np.median(times)), 3),
            "p90_ms": round(1e3 * float(np.quantile(times, 0.9)), 3)}
        if on:
            rows[name]["groups"] = len(engine.numerics.plan.group_names)
    rows["enabled_overhead_x"] = round(
        rows["on"]["median_ms"] / rows["off"]["median_ms"], 3)
    return rows


_HANG_SCRIPT = textwrap.dedent("""
    import sys
    sys.path.insert(0, sys.argv[3])
    sys.path.insert(0, sys.argv[4])
    import numpy as np
    import deepspeed_tpu
    from deepspeed_tpu.parallel.mesh import build_mesh
    from simple_model import mlp_params, mlp_loss_fn, random_batches

    engine, _, _, _ = deepspeed_tpu.initialize(
        loss_fn=mlp_loss_fn, params=mlp_params(),
        config={
            "train_micro_batch_size_per_gpu": 2,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
            "steps_per_print": 1000,
            "resilience": {"fault_injection": {
                "hang_at_step": int(sys.argv[2]), "hang_seconds": 120}},
            "guardrails": {"enabled": True,
                           "rollback": {"enabled": False},
                           "watchdog": {"enabled": True,
                                        "step_timeout_seconds": 1.0,
                                        "poll_interval_seconds": 0.05,
                                        "crashdump_dir": sys.argv[1]}},
        },
        mesh=build_mesh(data=8), rng_seed=0)
    rng = np.random.default_rng(7)
    for _ in range(8):
        engine.train_batch(random_batches(rng, 1, batch_size=16))
    print("UNREACHABLE: hang never fired", file=sys.stderr)
    sys.exit(1)
""")


def probe_watchdog(dump_dir):
    """Injected hang -> distinct rc + crashdump with thread stacks."""
    proc = subprocess.run(
        [sys.executable, "-c", _HANG_SCRIPT, dump_dir, "3", _ROOT,
         os.path.join(_ROOT, "tests")],
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
        capture_output=True, timeout=300)
    dumps = [d for d in (os.listdir(dump_dir) if os.path.isdir(dump_dir)
                         else []) if d.startswith("watchdog_")]
    stacks_ok = False
    if dumps:
        spath = os.path.join(dump_dir, dumps[0], "stacks.txt")
        stacks_ok = os.path.exists(spath) and "hang" in open(spath).read()
    return {
        "rc": proc.returncode,
        "distinct_rc": proc.returncode == GUARDRAILS_WATCHDOG_EXIT_CODE_DEFAULT,
        "crashdump": bool(dumps),
        "stacks_name_hang_site": stacks_ok,
    }


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--selftest", action="store_true",
                    help="short CI run: fewer steps, looser overhead gate")
    ap.add_argument("--steps", type=int, default=None)
    args = ap.parse_args(argv)
    steps = args.steps or (10 if args.selftest else 40)
    warmup = 2 if args.selftest else 8

    rows = {"config": {"model": "gpt-tiny-2layer", "steps": steps,
                       "warmup": warmup}}
    rows.update(probe_overhead(steps, warmup))
    root = tempfile.mkdtemp(prefix="guardrails_probe_")
    try:
        rows["numerics"] = probe_numerics(steps, warmup,
                                          os.path.join(root, "tel"))
        rows["watchdog"] = probe_watchdog(os.path.join(root, "dump"))
    finally:
        shutil.rmtree(root, ignore_errors=True)

    # Gates. The <5% target is the contract on real step times; a ~13ms
    # tiny-GPT CPU step is noise-dominated (p90 ~5x median on a busy
    # host), so the gate carries an absolute noise floor, like
    # probe_resilience_overhead's. The selftest keeps the watchdog
    # contract strict and the perf gate loose.
    off, on = rows["off"]["median_ms"], rows["on"]["median_ms"]
    floor_ms = 5.0 if args.selftest else 2.0
    rows["enabled_within_budget"] = bool(on <= off * 1.05 + floor_ms)
    # Numerics column rides the SAME noise-floored <5% gate: the
    # single-fetch claim is measured here, not asserted.
    noff = rows["numerics"]["off"]["median_ms"]
    non = rows["numerics"]["on"]["median_ms"]
    rows["numerics_within_budget"] = bool(non <= noff * 1.05 + floor_ms)
    wd = rows["watchdog"]
    rows["watchdog_ok"] = bool(wd["distinct_rc"] and wd["crashdump"]
                               and wd["stacks_name_hang_site"])
    print(json.dumps(rows, indent=1))
    return 0 if (rows["enabled_within_budget"]
                 and rows["numerics_within_budget"]
                 and rows["watchdog_ok"]) else 1


if __name__ == "__main__":
    sys.exit(main())
