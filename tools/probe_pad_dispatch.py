"""Round-4 probe: odd 128-multiple self-attention lengths (640/768/896/
1152) — xla fallback vs degraded-block pallas vs PADDED pallas (pad to
512-multiple, mask the tail). In-run A/B, 8-layer BERT-large-shaped
attention stacks, fwd+bwd, scalar-fence timing."""

import functools
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, "/root/repo")
from deepspeed_tpu.ops.transformer import attention as att  # noqa: E402

LAYERS, B, H, D = 8, 8, 16, 64


def stack_loss(q, k, v, impl, dropout_rng):
    rate = 0.0 if dropout_rng is None else 0.1
    x = q
    for i in range(LAYERS):
        rng = (None if dropout_rng is None
               else jax.random.fold_in(dropout_rng, i))
        x = att.attention(x, k, v, causal=False, impl=impl,
                          dropout_rate=rate, dropout_rng=rng,
                          deterministic=dropout_rng is None)
    return jnp.sum(x.astype(jnp.float32))


def timed(s, impl, dropout, steps=10, warmup=2):
    rng = np.random.default_rng(0)
    shape = (B, s, H, D)
    q = jnp.asarray(rng.standard_normal(shape), jnp.bfloat16) * 0.1
    k = jnp.asarray(rng.standard_normal(shape), jnp.bfloat16) * 0.1
    v = jnp.asarray(rng.standard_normal(shape), jnp.bfloat16) * 0.1
    key = jax.random.PRNGKey(1) if dropout else None

    grad = jax.jit(jax.grad(
        functools.partial(stack_loss, impl=impl, dropout_rng=key),
        argnums=(0, 1, 2)))
    for _ in range(warmup):
        g = grad(q, k, v)
    float(jnp.sum(g[0].astype(jnp.float32)))
    t0 = time.perf_counter()
    for _ in range(steps):
        g = grad(q, k, v)
    float(jnp.sum(g[0].astype(jnp.float32)))
    return (time.perf_counter() - t0) / steps * 1e3


def main():
    print("platform:", jax.devices()[0].platform, flush=True)
    for dropout in (False, True):
        for s in (640, 768, 896, 1152):
            xla = timed(s, "xla", dropout)
            deg = timed(s, "pallas", dropout)
            pad = timed(s, "pallas_pad", dropout)
            best = min((xla, "xla"), (deg, "pallas"), (pad, "pallas_pad"))
            print(f"seq {s:5d} dropout={int(dropout)}: xla {xla:6.1f}  "
                  f"pallas-degraded {deg:6.1f}  pallas-padded {pad:6.1f} ms"
                  f"  -> {best[1]}", flush=True)


if __name__ == "__main__":
    main()
