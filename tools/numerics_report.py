#!/usr/bin/env python
"""Render a run's numerics observatory metrics from its telemetry JSONL.

The numerics-side companion of goodput_report/fleet_report/memory_report
(docs/OBSERVABILITY.md "Numerics observatory"): feed it the run dir (the
job's ``telemetry.dir``) or metrics file(s) and it aggregates the
``numerics/*`` rows the engine emits —

- **per-layer-group trend table**: latest gradient norm, weight norm,
  update-to-weight ratio and dtype saturation/underflow counts per
  group, with the first->last update-ratio trajectory over the run;
- **monotone update-ratio drift flags**: a group whose update-to-weight
  ratio moves monotonically (non-decreasing or non-increasing, with at
  least one strict move) across >= ``--drift-window`` flushes AND by
  more than ``--drift-factor`` x overall is flagged — the slow-burn
  instability signature (a param tier decoupling from its gradient
  scale) that a single-step spike detector cannot see;
- **quantization-error table**: latest per-bucket DCN round-trip error
  (``numerics/dcn_quant_rel_err`` / ``_max_abs_err``) and per-bucket KV
  cache error (``numerics/kv_quant_rel_err``) — the measured
  accuracy/bandwidth evidence for the int8 wire paths;
- nonfinite values (a NaN'd group's gauges) are surfaced, never hidden.

    python tools/numerics_report.py /runs/exp17/telemetry
    python tools/numerics_report.py /runs/exp17/telemetry --json
    python tools/numerics_report.py --selftest

Standalone on purpose: stdlib only, so it runs anywhere the run dir
lands (including hosts without jax installed). Keep the tag strings in
sync with deepspeed_tpu/telemetry/numerics.py NUMERICS_METRIC_TAGS —
tests/test_doc_lint.py pins them.
"""

import argparse
import glob
import json
import math
import os
import sys
import tempfile
from typing import Any, Dict, List, Tuple

DEFAULT_METRICS_FILE = "metrics.jsonl"

# Per-group gauges (tagged group=<name>), in table-column order.
GROUP_TAGS = (
    "numerics/grad_norm",
    "numerics/weight_norm",
    "numerics/update_ratio",
    "numerics/saturation_count",
    "numerics/underflow_count",
)
# Per-bucket quantization-error gauges (tagged bucket=<i>).
QUANT_TAGS = (
    "numerics/dcn_quant_rel_err",
    "numerics/dcn_quant_max_abs_err",
    "numerics/kv_quant_rel_err",
    "numerics/kv_quant_max_abs_err",
)
GLOBAL_TAGS = ("numerics/global_grad_norm",)


def _metric_files(path: str) -> List[str]:
    """A metrics file, or every (possibly host-scoped) metrics*.jsonl
    under a run dir — the fleet_report convention."""
    if os.path.isfile(path):
        return [path]
    pattern = os.path.join(path, "metrics*.jsonl")
    return sorted(glob.glob(pattern))


def load_rows(paths: List[str]) -> List[Dict[str, Any]]:
    rows: List[Dict[str, Any]] = []
    for path in paths:
        try:
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        row = json.loads(line)
                    except ValueError:
                        continue
                    tag = row.get("tag", "")
                    if tag.startswith("numerics/"):
                        rows.append(row)
        except OSError:
            continue
    return rows


def _series(rows: List[Dict[str, Any]], tag: str,
            key: str) -> Dict[Any, List[Tuple[int, float]]]:
    """tag rows -> {key_value: [(step, value), ...] sorted by step}."""
    out: Dict[Any, List[Tuple[int, float]]] = {}
    for r in rows:
        if r.get("tag") != tag or key not in r:
            continue
        out.setdefault(r[key], []).append(
            (int(r.get("step", 0)), float(r.get("value", 0.0))))
    for v in out.values():
        v.sort(key=lambda t: t[0])
    return out


def detect_drift(values: List[float], window: int = 4,
                 factor: float = 2.0) -> bool:
    """Monotone update-ratio drift: over the last ``window`` (or more)
    observations the series never reverses direction, moves strictly at
    least once, and the overall multiplicative change exceeds
    ``factor`` (or falls below 1/factor). Nonfinite values disable the
    verdict — a NaN'd group is a spike story, not a drift story."""
    tail = values[-max(int(window), 2):]
    if len(tail) < max(int(window), 2):
        return False
    if any(not math.isfinite(v) for v in tail):
        return False
    diffs = [b - a for a, b in zip(tail, tail[1:])]
    up = all(d >= 0 for d in diffs) and any(d > 0 for d in diffs)
    down = all(d <= 0 for d in diffs) and any(d < 0 for d in diffs)
    if not (up or down):
        return False
    lo, hi = tail[0], tail[-1]
    if up:
        return hi > lo * factor if lo > 0 else hi > 0
    return lo > hi * factor if hi > 0 else lo > 0


def build_report(rows: List[Dict[str, Any]], window: int = 4,
                 factor: float = 2.0) -> Dict[str, Any]:
    groups: Dict[str, Dict[str, Any]] = {}
    per_tag = {tag: _series(rows, tag, "group") for tag in GROUP_TAGS}
    names = sorted({g for s in per_tag.values() for g in s})
    for name in names:
        row: Dict[str, Any] = {"group": name}
        for tag in GROUP_TAGS:
            series = per_tag[tag].get(name, [])
            short = tag.split("/", 1)[1]
            row[short] = series[-1][1] if series else None
            if tag == "numerics/update_ratio" and series:
                vals = [v for _, v in series]
                row["update_ratio_first"] = vals[0]
                row["update_ratio_drift"] = detect_drift(
                    vals, window=window, factor=factor)
                row["observations"] = len(vals)
        row["nonfinite"] = any(
            row.get(t.split("/", 1)[1]) is not None
            and not math.isfinite(row[t.split("/", 1)[1]])
            for t in GROUP_TAGS)
        groups[name] = row
    quant: Dict[str, Dict[Any, float]] = {}
    for tag in QUANT_TAGS:
        series = _series(rows, tag, "bucket")
        if series:
            quant[tag] = {b: s[-1][1] for b, s in series.items()}
    glob_series = _series(
        [dict(r, _one=1) for r in rows if r.get("tag") in GLOBAL_TAGS],
        "numerics/global_grad_norm", "_one").get(1, [])
    drifting = sorted(g for g, r in groups.items()
                      if r.get("update_ratio_drift"))
    return {
        "groups": [groups[n] for n in names],
        "quant": quant,
        "global_grad_norm": glob_series[-1][1] if glob_series else None,
        "drifting_groups": drifting,
        "n_rows": len(rows),
    }


def _fmt(v, width=11) -> str:
    if v is None:
        return f"{'-':>{width}}"
    if isinstance(v, bool):
        return f"{('DRIFT' if v else 'ok'):>{width}}"
    if isinstance(v, float) and not math.isfinite(v):
        return f"{'nonfinite':>{width}}"
    return f"{v:>{width}.4g}"


def render(report: Dict[str, Any]) -> str:
    out = ["numerics observatory report", ""]
    hdr = (f"{'group':<18} {'grad_norm':>11} {'weight_norm':>11} "
           f"{'upd_ratio':>11} {'ratio_t0':>11} {'sat':>6} {'under':>6} "
           f"  drift")
    out.append(hdr)
    out.append("-" * len(hdr))
    for g in report["groups"]:
        sat = g.get("saturation_count")
        under = g.get("underflow_count")
        out.append(
            f"{g['group']:<18} {_fmt(g.get('grad_norm'))} "
            f"{_fmt(g.get('weight_norm'))} {_fmt(g.get('update_ratio'))} "
            f"{_fmt(g.get('update_ratio_first'))} "
            f"{int(sat) if sat is not None else '-':>6} "
            f"{int(under) if under is not None else '-':>6} "
            f"  {'DRIFT' if g.get('update_ratio_drift') else 'ok'}")
    if report.get("global_grad_norm") is not None:
        out.append("")
        out.append(f"global grad norm (last flush): "
                   f"{report['global_grad_norm']:.6g}")
    if report["quant"]:
        out.append("")
        out.append("quantization round-trip error (last flush, per bucket):")
        for tag, buckets in sorted(report["quant"].items()):
            vals = ", ".join(f"[{b}] {v:.4g}"
                             for b, v in sorted(buckets.items()))
            out.append(f"  {tag}: {vals}")
    out.append("")
    if report["drifting_groups"]:
        out.append("MONOTONE UPDATE-RATIO DRIFT: "
                   + ", ".join(report["drifting_groups"])
                   + " — update/weight scale is walking; check LR "
                     "schedule / weight decay before it spikes")
    else:
        out.append("no monotone update-ratio drift detected")
    return "\n".join(out)


# ---------------------------------------------------------------------------
# Selftest
# ---------------------------------------------------------------------------

def _selftest() -> int:
    assert detect_drift([1, 2, 4, 9], 4, 2.0)
    assert detect_drift([8, 4, 2, 1], 4, 2.0)          # downward counts
    assert not detect_drift([1, 2, 1, 2], 4, 2.0)      # not monotone
    assert not detect_drift([1.0, 1.1, 1.2, 1.3], 4, 2.0)  # under factor
    assert not detect_drift([1, 2, 4], 4, 2.0)         # too short
    assert not detect_drift([1, 2, float("nan"), 9], 4, 2.0)

    with tempfile.TemporaryDirectory() as td:
        path = os.path.join(td, "metrics.jsonl")
        rows = []
        # `head` drifts monotonically x8; `layer_0` stays flat.
        for i, step in enumerate((5, 10, 15, 20)):
            for grp, ratio in (("head", 0.001 * (2 ** i)),
                               ("layer_0", 0.001)):
                rows.append({"tag": "numerics/update_ratio", "value": ratio,
                             "step": step, "kind": "gauge", "group": grp})
                rows.append({"tag": "numerics/grad_norm", "value": 0.1,
                             "step": step, "kind": "gauge", "group": grp})
                rows.append({"tag": "numerics/weight_norm", "value": 1.0,
                             "step": step, "kind": "gauge", "group": grp})
                rows.append({"tag": "numerics/saturation_count", "value": 0,
                             "step": step, "kind": "gauge", "group": grp})
                rows.append({"tag": "numerics/underflow_count", "value": 2,
                             "step": step, "kind": "gauge", "group": grp})
            rows.append({"tag": "numerics/global_grad_norm", "value": 0.14,
                         "step": step, "kind": "gauge"})
            rows.append({"tag": "numerics/dcn_quant_rel_err", "value": 0.008,
                         "step": step, "kind": "gauge", "bucket": 0})
        with open(path, "w") as f:
            for r in rows:
                f.write(json.dumps(r) + "\n")
        report = build_report(load_rows(_metric_files(td)))
        assert report["drifting_groups"] == ["head"], report
        head = next(g for g in report["groups"] if g["group"] == "head")
        assert head["update_ratio_drift"] and head["observations"] == 4
        flat = next(g for g in report["groups"] if g["group"] == "layer_0")
        assert not flat["update_ratio_drift"]
        assert report["quant"]["numerics/dcn_quant_rel_err"][0] == 0.008
        assert report["global_grad_norm"] == 0.14
        text = render(report)
        assert "DRIFT" in text and "head" in text
        assert "dcn_quant_rel_err" in text
        # CLI round-trip on the same dir
        assert main([td]) == 0
    print("\nselftest ok")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("path", nargs="?",
                    help="telemetry run dir or metrics JSONL file")
    ap.add_argument("--drift-window", type=int, default=4,
                    help="observations the monotone-drift flag needs "
                         "(default 4)")
    ap.add_argument("--drift-factor", type=float, default=2.0,
                    help="overall change factor that counts as drift "
                         "(default 2.0)")
    ap.add_argument("--json", action="store_true",
                    help="emit the report as JSON")
    ap.add_argument("--selftest", action="store_true",
                    help="run the built-in check and exit")
    args = ap.parse_args(argv)
    if args.selftest:
        return _selftest()
    if not args.path:
        ap.error("run dir or metrics file required (or --selftest)")
    files = _metric_files(args.path)
    if not files:
        print(f"no metrics*.jsonl under {args.path}", file=sys.stderr)
        return 1
    rows = load_rows(files)
    if not rows:
        print(f"no numerics/* rows in {files} — is telemetry.numerics "
              f"enabled?", file=sys.stderr)
        return 1
    report = build_report(rows, window=args.drift_window,
                          factor=args.drift_factor)
    if args.json:
        print(json.dumps(report, indent=1))
    else:
        print(render(report))
    return 0


if __name__ == "__main__":
    sys.exit(main())
