#!/usr/bin/env python
"""Render per-request SLO evidence from the request observatory's files.

The request-level companion of serving_report: where serving_report reads
the engine's aggregate ``serving/*`` gauges, this merges the PER-REQUEST
records (``requests*.jsonl`` — one JSON object per finished request,
host-scoped like ``metrics.<host>.jsonl``) with the ``requests/*`` metric
rows from every host/replica in a run dir and renders

- **latency percentiles**: TTFT / TPOT (inter-token) / e2e / queue wait,
  p50/p90/p99 tables — the SLO surface the scale-out router ranks
  replicas with;
- **time lost per category**: the exact lifetime partition summed over
  requests (queue_wait / prefill / decode_active / preempted_requeue /
  spec_overhead / finish_other), seconds + share — "where did the fleet's
  request-seconds go";
- **engine serving-time partition**: what fraction of each engine's wall
  clock produced tokens (prefill / decode / scheduler+admission /
  host_idle / compile), summed across host files;
- **prefix-cache savings attribution** (tokens the warm heads skipped)
  and preemption counts.

    python tools/slo_report.py /runs/serve17/telemetry
    python tools/slo_report.py /runs/serve17/telemetry --json
    python tools/slo_report.py --selftest

Standalone on purpose: stdlib only, so it runs anywhere the run dir
lands. Keep the tag strings in sync with
deepspeed_tpu/telemetry/requests.py REQUEST_METRIC_TAGS —
tests/test_doc_lint.py pins them.
"""

import argparse
import glob
import json
import os
import sys
import tempfile
from typing import Any, Dict, List, Optional

DEFAULT_REQUESTS_FILE = "requests.jsonl"
DEFAULT_METRICS_FILE = "metrics.jsonl"

# Mirrors telemetry/requests.py REQUEST_CATEGORIES / ENGINE_CATEGORIES
# (stdlib-only tool: no package import; the doc-lint sync test pins the
# metric tags below against REQUEST_METRIC_TAGS).
CATEGORIES = ("queue_wait", "prefill", "decode_active",
              "preempted_requeue", "spec_overhead", "finish_other")
ENGINE_CATEGORIES = ("prefill", "decode", "scheduler_admission",
                     "host_idle", "compile")

TPOT_TAG = "requests/tpot_ms"
ENGINE_WALL_TAG = "requests/engine_wall_sec"

# Terminal statuses a record can carry (serving/resilience.py
# TERMINAL_STATUSES; records predating the status field are finished).
STATUSES = ("finished", "shed", "deadline_expired", "cancelled", "aborted")


def _percentile(sorted_vals: List[float], q: float) -> float:
    """Linear-interpolated percentile over an already-sorted list."""
    if not sorted_vals:
        return float("nan")
    if len(sorted_vals) == 1:
        return sorted_vals[0]
    pos = (q / 100.0) * (len(sorted_vals) - 1)
    lo = int(pos)
    hi = min(lo + 1, len(sorted_vals) - 1)
    return sorted_vals[lo] + (sorted_vals[hi] - sorted_vals[lo]) * (pos - lo)


def _pcts(vals: List[float]) -> Optional[Dict[str, float]]:
    vals = sorted(v for v in vals if v is not None)
    if not vals:
        return None
    return {"p50": _percentile(vals, 50), "p90": _percentile(vals, 90),
            "p99": _percentile(vals, 99), "n": len(vals)}


def _iter_json_lines(path: str):
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                row = json.loads(line)
            except json.JSONDecodeError:
                continue          # torn tail line of a live/killed run
            if isinstance(row, dict):
                yield row


def _glob(run_dir: str, filename: str) -> List[str]:
    stem, ext = os.path.splitext(filename)
    return sorted(glob.glob(os.path.join(run_dir, f"{stem}*{ext}")))


def collect(run_dir: str,
            requests_file: str = DEFAULT_REQUESTS_FILE,
            metrics_file: str = DEFAULT_METRICS_FILE) -> Dict[str, Any]:
    """Merge ``requests*.jsonl`` records + ``requests/*`` metric rows
    from every host-scoped file in the run dir."""
    rec_paths = _glob(run_dir, requests_file)
    records: List[Dict[str, Any]] = []
    for path in rec_paths:
        for row in _iter_json_lines(path):
            if "rid" in row and "e2e_ms" in row:
                records.append(row)

    # requests/tpot_ms histogram rows carry EVERY inter-token interval —
    # the true TPOT distribution (per-record tpot_mean_ms is the
    # fallback when only records landed). The engine-partition gauges
    # are cumulative: last value per host file, hosts sum.
    tpot_obs: List[float] = []
    engine_part: Dict[str, float] = {}
    engine_wall = 0.0
    met_paths = _glob(run_dir, metrics_file)
    for path in met_paths:
        last: Dict[str, float] = {}
        for row in _iter_json_lines(path):
            tag = row.get("tag")
            if not isinstance(tag, str) or not tag.startswith("requests/"):
                continue
            val = float(row.get("value", 0.0))
            if tag == TPOT_TAG:
                tpot_obs.append(val)
            elif tag.startswith("requests/engine_"):
                last[tag] = val
        for c in ENGINE_CATEGORIES:
            tag = f"requests/engine_{c}_sec"
            if tag in last:
                engine_part[c] = engine_part.get(c, 0.0) + last[tag]
        if ENGINE_WALL_TAG in last:
            engine_wall += last[ENGINE_WALL_TAG]

    # Terminal-status breakdown: percentiles are computed over ADMITTED
    # requests only — a shed request's sub-millisecond "e2e" is a policy
    # artifact, and mixing it in would make an overloaded, shedding
    # engine look faster than a healthy one. Records predating the
    # status/admitted fields count as admitted+finished.
    status_counts: Dict[str, int] = {}
    for r in records:
        s = r.get("status", "finished")
        status_counts[s] = status_counts.get(s, 0) + 1
    admitted = [r for r in records if r.get("admitted", True)]

    report: Dict[str, Any] = {
        "record_files": [os.path.basename(p) for p in rec_paths],
        "metric_files": [os.path.basename(p) for p in met_paths],
        "n_requests": len(records),
        "n_admitted": len(admitted),
        "status_counts": status_counts,
        "shed_frac": (status_counts.get("shed", 0) / len(records)
                      if records else None),
        "hosts": sorted({r.get("host") for r in records
                         if r.get("host") is not None}),
    }
    report["ttft_ms"] = _pcts([r.get("ttft_ms") for r in admitted])
    report["tpot_ms"] = (_pcts(tpot_obs) if tpot_obs
                         else _pcts([r.get("tpot_mean_ms")
                                     for r in admitted]))
    report["tpot_source"] = ("metrics" if tpot_obs
                             else "records" if admitted else None)
    report["e2e_ms"] = _pcts([r.get("e2e_ms") for r in admitted])
    report["queue_wait_ms"] = _pcts([r.get("queue_wait_ms")
                                     for r in admitted])

    # -- time lost per category (exact partition, summed) ---------------
    cat_sec = {c: 0.0 for c in CATEGORIES}
    for r in admitted:
        cats = r.get("categories") or {}
        for c in CATEGORIES:
            cat_sec[c] += float(cats.get(c, 0.0))
    total_sec = sum(cat_sec.values())
    report["category_sec"] = cat_sec
    report["category_frac"] = (
        {c: cat_sec[c] / total_sec for c in CATEGORIES}
        if total_sec > 0 else None)
    report["total_request_sec"] = total_sec

    # -- engine serving-time partition -----------------------------------
    report["engine_partition_sec"] = engine_part or None
    report["engine_wall_sec"] = engine_wall or None
    report["engine_decode_frac"] = (
        engine_part.get("decode", 0.0) / engine_wall
        if engine_part and engine_wall else None)

    # -- prefix-cache savings + preemption -------------------------------
    report["prefix_tokens_saved"] = sum(
        int(r.get("prefix_tokens_saved") or 0) for r in records)
    report["requests_with_prefix_hit"] = sum(
        1 for r in records if (r.get("prefix_tokens_saved") or 0) > 0)
    report["preemptions"] = sum(
        int(r.get("preempted_count") or 0) for r in records)
    report["requests_preempted"] = sum(
        1 for r in records if (r.get("preempted_count") or 0) > 0)
    return report


def render(report: Dict[str, Any]) -> str:
    out = ["request SLO report"]
    out.append(f"  records: {', '.join(report['record_files']) or '<none>'}"
               f"  ({report['n_requests']} requests"
               + (f", hosts {', '.join(report['hosts'])}"
                  if report["hosts"] else "") + ")")
    counts = report.get("status_counts") or {}
    if set(counts) - {"finished"}:
        parts = [f"{s} {counts[s]}" for s in STATUSES if counts.get(s)]
        parts += [f"{s} {n}" for s, n in sorted(counts.items())
                  if s not in STATUSES]
        shed = report.get("shed_frac") or 0.0
        out.append(f"  terminal status  {'  '.join(parts)}"
                   f"  (shed {shed:.1%}; percentiles over "
                   f"{report['n_admitted']} admitted)")
    for label, key in (("TTFT", "ttft_ms"), ("TPOT", "tpot_ms"),
                       ("e2e", "e2e_ms"), ("queue wait", "queue_wait_ms")):
        p = report.get(key)
        if p:
            src = (f"  [{report['tpot_source']}, {p['n']} obs]"
                   if key == "tpot_ms" else "")
            out.append(f"  {label:<11} p50 {p['p50']:9.1f} ms   "
                       f"p90 {p['p90']:9.1f} ms   "
                       f"p99 {p['p99']:9.1f} ms{src}")
    if report["total_request_sec"] > 0:
        out.append(f"  time lost per category "
                   f"({report['total_request_sec']:.2f} request-seconds "
                   f"total):")
        frac = report["category_frac"]
        for c in CATEGORIES:
            out.append(f"    {c:<18} {report['category_sec'][c]:10.3f} s  "
                       f"{frac[c]:7.1%}")
    ep = report.get("engine_partition_sec")
    if ep:
        wall = report.get("engine_wall_sec") or 0.0
        out.append(f"  engine serving-time partition "
                   f"({wall:.2f} s wall):")
        for c in ENGINE_CATEGORIES:
            sec = ep.get(c, 0.0)
            share = f"{sec / wall:7.1%}" if wall else "      -"
            out.append(f"    {c:<18} {sec:10.3f} s  {share}")
    if report["requests_with_prefix_hit"]:
        out.append(f"  prefix cache    {report['prefix_tokens_saved']} "
                   f"prompt tokens skipped across "
                   f"{report['requests_with_prefix_hit']} warm requests")
    if report["preemptions"]:
        out.append(f"  preemptions     {report['preemptions']} across "
                   f"{report['requests_preempted']} requests")
    if not report["n_requests"]:
        out.append("  (no request records found — was the engine run with "
                   "telemetry.requests enabled?)")
    return "\n".join(out)


# ---------------------------------------------------------------------------
# Selftest
# ---------------------------------------------------------------------------

def _selftest() -> int:
    """Synthesize host-scoped request records (+ a torn tail) and a
    metrics file, then assert the merged percentiles, the category
    table and the savings attribution."""
    def rec(rid, host, e2e, ttft, tpot, qw, cats, prefix=0, preempted=0):
        return {"format": 1, "rid": rid, "host": host, "prompt_len": 8,
                "new_tokens": 4, "finish_step": rid, "e2e_ms": e2e,
                "ttft_ms": ttft, "tpot_mean_ms": tpot, "queue_wait_ms": qw,
                "prefix_tokens_saved": prefix, "preempted_count": preempted,
                "lifetime_sec": e2e / 1e3, "categories": cats}

    def cats(**kw):
        d = {c: 0.0 for c in CATEGORIES}
        d.update(kw)
        return d

    with tempfile.TemporaryDirectory() as td:
        recs_a = [rec(i, "hostA", e2e=100.0 + 10 * i, ttft=10.0 + i,
                      tpot=2.0 + 0.1 * i, qw=5.0,
                      cats=cats(queue_wait=0.005, prefill=0.01,
                                decode_active=0.08))
                  for i in range(10)]
        with open(os.path.join(td, "requests.hostA.jsonl"), "w") as f:
            for r in recs_a:
                f.write(json.dumps(r) + "\n")
            f.write('{"rid": 99, "torn')            # must be tolerated
        with open(os.path.join(td, "requests.hostB.jsonl"), "w") as f:
            f.write(json.dumps(rec(
                0, "hostB", e2e=500.0, ttft=50.0, tpot=4.0, qw=200.0,
                cats=cats(queue_wait=0.2, prefill=0.05, decode_active=0.2,
                          preempted_requeue=0.05),
                prefix=16, preempted=1)) + "\n")
        with open(os.path.join(td, "metrics.hostA.jsonl"), "w") as f:
            for i, v in enumerate((1.0, 2.0, 3.0, 4.0)):
                f.write(json.dumps(
                    {"tag": "requests/tpot_ms", "value": v, "step": i,
                     "kind": "histogram"}) + "\n")
            for tag, v in (("requests/engine_prefill_sec", 0.5),
                           ("requests/engine_decode_sec", 2.0),
                           ("requests/engine_scheduler_admission_sec", 0.1),
                           ("requests/engine_host_idle_sec", 0.3),
                           ("requests/engine_compile_sec", 1.0),
                           ("requests/engine_wall_sec", 4.0)):
                f.write(json.dumps({"tag": tag, "value": v, "step": 9,
                                    "kind": "gauge"}) + "\n")

        # Terminal-status records (serving/resilience.py): shed/expired
        # requests must show in the breakdown but NOT in the percentiles
        # — their sub-ms "latency" would fake a fast engine.
        with open(os.path.join(td, "requests.hostA.jsonl"), "a") as f:
            f.write("\n")                 # terminate the torn tail line
            for i, status in enumerate(("shed", "shed",
                                        "deadline_expired")):
                f.write(json.dumps(
                    {"format": 1, "rid": 100 + i, "host": "hostA",
                     "status": status, "admitted": False,
                     "prompt_len": 8, "new_tokens": 0, "finish_step": 0,
                     "e2e_ms": 0.3, "ttft_ms": None,
                     "queue_wait_ms": None}) + "\n")

        report = collect(td)
        assert report["n_requests"] == 14, report
        assert report["n_admitted"] == 11, report
        assert report["status_counts"] == {
            "finished": 11, "shed": 2, "deadline_expired": 1}, report
        assert abs(report["shed_frac"] - 2 / 14) < 1e-9, report
        # admitted-only percentiles: the 0.3ms shed rows must not drag
        # e2e down
        assert report["e2e_ms"]["n"] == 11, report
        assert report["hosts"] == ["hostA", "hostB"], report
        # e2e over 100..190 + 500: p50 is the 6th of 11 sorted values
        assert abs(report["e2e_ms"]["p50"] - 150.0) < 1e-6, report
        assert report["e2e_ms"]["p99"] > 190.0, report
        assert abs(report["ttft_ms"]["p50"] - 15.0) < 1e-6, report
        # TPOT prefers the metric observations (1, 2, 3, 4 -> p50 2.5)
        assert report["tpot_source"] == "metrics", report
        assert abs(report["tpot_ms"]["p50"] - 2.5) < 1e-6, report
        # category table sums across hosts
        assert abs(report["category_sec"]["decode_active"]
                   - (0.08 * 10 + 0.2)) < 1e-9, report
        assert abs(report["category_sec"]["preempted_requeue"]
                   - 0.05) < 1e-9, report
        assert report["category_frac"]["decode_active"] > 0.5, report
        # engine partition: last gauge value per file
        assert report["engine_partition_sec"]["decode"] == 2.0, report
        assert abs(report["engine_decode_frac"] - 0.5) < 1e-6, report
        assert report["prefix_tokens_saved"] == 16, report
        assert report["requests_with_prefix_hit"] == 1, report
        assert report["preemptions"] == 1, report
        text = render(report)
        assert "TPOT" in text and "time lost" in text
        assert "prefix cache" in text and "preemptions" in text
        assert "engine serving-time partition" in text
        assert "terminal status" in text and "shed 2" in text, text
        assert "11 admitted" in text, text
        json.dumps(report)                          # serializable

        # TPOT falls back to per-record means without metric rows
        os.remove(os.path.join(td, "metrics.hostA.jsonl"))
        report = collect(td)
        assert report["tpot_source"] == "records", report
        assert report["tpot_ms"]["n"] == 11, report
    print("\nselftest ok")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("run_dir", nargs="?",
                    help="the job's telemetry.dir (holds requests*.jsonl "
                         "+ metrics*.jsonl)")
    ap.add_argument("--requests-file", default=DEFAULT_REQUESTS_FILE)
    ap.add_argument("--metrics-file", default=DEFAULT_METRICS_FILE)
    ap.add_argument("--json", action="store_true",
                    help="machine-readable report")
    ap.add_argument("--selftest", action="store_true",
                    help="run the built-in round-trip check and exit")
    args = ap.parse_args(argv)
    if args.selftest:
        return _selftest()
    if not args.run_dir:
        ap.error("run dir required (or --selftest)")
    report = collect(args.run_dir, requests_file=args.requests_file,
                     metrics_file=args.metrics_file)
    if args.json:
        print(json.dumps(report, indent=1, sort_keys=True))
    else:
        print(render(report))
    return 0


if __name__ == "__main__":
    sys.exit(main())
