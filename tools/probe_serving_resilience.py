"""Acceptance probe: serving survives chaos with token-identical output.

The claims of docs/SERVING.md "Serving under failure", measured on a tiny
GPT over the CPU backend:

1. **Chaos → recover → token identity** — with an injected
   decode-dispatch fault mid-trace (FaultPlan ``serve_decode_fault``),
   the engine retries, rebuilds its KV pools + decode programs
   in-process, replays every live sequence, and every request finishes
   with output byte-identical to the fault-free run. A persistent-fault
   window (wider than the retry budget) forces the full rebuild path and
   still matches.
2. **Leak-free terminal aborts** — deadline expiry and cancellation
   release every KV block exactly once: after a chaos trace with aborts
   the pool drains to zero (the BlockPool refcounts raise on any double
   free, so this is structural, not statistical).
3. **Shed-fraction gate** — under a FaultPlan request storm with
   admission control on, the engine sheds a bounded fraction: some
   requests shed (the gate works), but never ALL of them (admitted work
   keeps flowing), and every shed rid has a terminal ``shed`` record.

Run: JAX_PLATFORMS=cpu python tools/probe_serving_resilience.py [--selftest]
(tier-1 via tests/test_serving_resilience.py)
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_ROOT = os.path.join(os.path.dirname(os.path.abspath(__file__)), os.pardir)
sys.path.insert(0, _ROOT)

TRACE = [(5, 10), (9, 4), (3, 8), (12, 5), (7, 7)]


def _build(params_model, fault=None, **overrides):
    import jax.numpy as jnp

    import deepspeed_tpu
    from deepspeed_tpu.config.config import ServingConfig
    from deepspeed_tpu.resilience import FaultPlan
    from deepspeed_tpu.serving import ServeEngine

    model, params = params_model
    scfg = ServingConfig(**{"max_batch_size": 2, "kv_block_size": 4,
                            "kv_num_blocks": 64, "max_model_len": 48,
                            **overrides})
    eng = deepspeed_tpu.init_inference(model, params=params,
                                       dtype=jnp.float32)
    plan = FaultPlan.resolve(fault) if fault else None
    return ServeEngine(eng, config=scfg, fault_plan=plan)


def _run_trace(srv, prompts, outs):
    rids = [srv.submit(p, n) for p, n in zip(prompts, outs)]
    res = srv.run_until_complete(timeout_sec=120.0)
    return [res[r]["tokens"] for r in rids]


def main(argv=None) -> int:
    selftest = "--selftest" in (argv if argv is not None else sys.argv[1:])
    import jax
    import jax.numpy as jnp
    import numpy as np

    from deepspeed_tpu.models import make_gpt

    model, cfg = make_gpt("tiny", dropout_rate=0.0, max_seq_len=64,
                          dtype=jnp.float32)
    params = model.init({"params": jax.random.PRNGKey(0),
                         "dropout": jax.random.PRNGKey(1)},
                        {"input_ids": np.zeros((1, 8), np.int32)})["params"]
    pm = (model, params)
    rng = np.random.default_rng(7)
    prompts = [rng.integers(0, cfg.vocab_size, (t,)).tolist()
               for t, _ in TRACE]
    outs = [n for _, n in TRACE]

    # -- 1. chaos -> recover -> token identity --------------------------
    base = _run_trace(_build(pm), prompts, outs)
    for name, fault in (
            ("transient (retry heals)",
             {"serve_decode_fault_at_step": 4}),
            ("persistent (rebuild+replay)",
             {"serve_decode_fault_at_step": 4,
              "serve_decode_fault_count": 3})):
        srv = _build(pm, fault=fault, resilience=True,
                     resil_retry_base_sec=0.01)
        got = _run_trace(srv, prompts, outs)
        assert got == base, f"{name}: outputs diverged from fault-free run"
        c = srv._resil.counters
        print(f"chaos [{name}]: retries={c['retries']} "
              f"recoveries={c['recoveries']} — all {len(TRACE)} requests "
              f"token-identical to the fault-free run")
        if "persistent" in name:
            assert c["recoveries"] >= 1, c
        else:
            assert c["retries"] >= 1 and c["recoveries"] == 0, c

    # -- 2. leak-free terminal aborts -----------------------------------
    srv = _build(pm, resilience=True)
    rids = [srv.submit(p, n) for p, n in zip(prompts, outs)]
    srv.step()                               # admit + first tokens
    assert srv.cancel(rids[0])
    srv.run_until_complete(timeout_sec=120.0)
    assert srv.results[rids[0]]["status"] == "cancelled", srv.results[rids[0]]
    assert srv.pool.used_blocks == 0, (
        f"leak: {srv.pool.used_blocks} blocks held after drain with a "
        f"cancelled request")
    print(f"terminal aborts: cancel keeps partial output "
          f"({len(srv.results[rids[0]]['tokens'])} tokens), pool drains "
          f"to 0")

    # -- 3. shed-fraction gate under a request storm --------------------
    srv = _build(pm, fault={"serve_storm_at_step": 2,
                            "serve_storm_requests": 12},
                 resilience=True, resil_max_queue_depth=3)
    shed_rids = [srv.submit(p, n) for p, n in zip(prompts, outs)]
    res = srv.run_until_complete(timeout_sec=120.0)
    statuses = [r["status"] for r in res.values()]
    n_shed = statuses.count("shed")
    n_fin = statuses.count("finished")
    assert n_shed > 0, "storm over a depth-3 queue shed nothing"
    assert n_fin >= len(TRACE), (
        f"admitted work starved: only {n_fin} finished under the storm")
    assert all(res[r]["status"] in ("finished", "shed")
               for r in shed_rids), "a submitted rid lost its record"
    frac = n_shed / len(res)
    print(f"load shedding: {n_shed}/{len(res)} shed ({frac:.0%}), "
          f"{n_fin} finished — admitted work kept flowing")
    assert 0.0 < frac < 1.0

    if selftest:
        print("selftest ok")
    return 0


if __name__ == "__main__":
    sys.exit(main())
