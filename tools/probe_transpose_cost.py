"""How much do the [B,S,H,D]<->[B*H,S,D] layout moves around the flash
kernel cost at bench shapes? 12-layer fwd+bwd loops, one process, real
chip. If this is <2% of the microbatch, the packed-layout kernel isn't
worth building."""

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, "/root/repo")

from deepspeed_tpu.ops.transformer.flash_attention import _flash_bhsd


def bench(name, fn, *args, steps=20):
    f = jax.jit(fn)
    out = f(*args)
    _ = float(jnp.sum(out).astype(jnp.float32))
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(steps):
            out = f(*args)
        _ = float(jnp.sum(out).astype(jnp.float32))
        best = min(best, (time.perf_counter() - t0) / steps)
    print(f"[{name}] {best * 1e3:.3f} ms", flush=True)
    return best


def main(b=16, s=512, h=12, d=64, layers=12):
    rng = np.random.default_rng(0)
    seed = jnp.zeros((1,), jnp.int32)
    scale = 1.0 / d ** 0.5
    x_bhsd = jnp.asarray(rng.standard_normal((b * h, s, d)), jnp.bfloat16)
    x_bshd = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.bfloat16)

    def flash(q):  # layout-native: no moves
        def body(h_, _):
            o = _flash_bhsd(h_, h_, h_, seed, True, scale, 512, 512,
                            False, 0.0)
            return o, None
        out, _ = jax.lax.scan(body, q, None, length=layers)
        return jnp.sum(out.astype(jnp.float32))

    def flash_t(q):  # model layout: transpose in+out each layer
        def body(h_, _):
            qt = h_.transpose(0, 2, 1, 3).reshape(b * h, s, d)
            o = _flash_bhsd(qt, qt, qt, seed, True, scale, 512, 512,
                            False, 0.0)
            o = o.reshape(b, h, s, d).transpose(0, 2, 1, 3)
            return o, None
        out, _ = jax.lax.scan(body, x_bshd, None, length=layers)
        return jnp.sum(out.astype(jnp.float32))

    print("platform:", jax.devices()[0].platform, flush=True)
    bench("fwd   native   ", flash, x_bhsd)
    bench("fwd   transpose", flash_t, x_bshd)
    bench("f+b   native   ", jax.grad(flash), x_bhsd)
    bench("f+b   transpose", jax.grad(flash_t), x_bshd)


if __name__ == "__main__":
    main()
