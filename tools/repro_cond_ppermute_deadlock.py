"""Pinned repro — XLA:CPU second-step rendezvous deadlock:
rank-divergent lax.cond inside a ppermute pipeline × ZeRO-1 apply
collectives (docs/ISSUES.md #1, round-5 bisection).

The pipelined train step wraps each tick's stage compute in
``lax.cond(valid, stage, passthrough)`` (the 1F1B bubble skip). On
XLA:CPU with 8 virtual devices, mesh (pipe=2, data=4):

- ZERO=0 (no optimizer-state sharding): 3 steps run fine — the cond
  itself is sound, fwd+bwd+apply all pass repeatedly.
- ZERO=1 (optimizer state sharded over `data` → all-gather collectives
  in the apply): the FIRST step completes, the SECOND deadlocks —

      F rendezvous.cc:127 Termination timeout for `collective permute
      ...` of 40 seconds exceeded. Expected 8 threads to join the
      rendezvous, but only 4 of them arrived on time.

  Removing the cond (SKIP=0) fixes it; removing ZeRO-1 fixes it; first
  execution never deadlocks. The bug needs the cond-divergent pipe
  groups AND a second collective family (the data-axis gathers) AND a
  prior execution of the same donated-buffer executable.

On TPU the pattern is standard (no thread-rendezvous execution model),
so the framework enables the bubble skip on TPU and keeps the
always-execute form on CPU (`DSTPU_SKIP_BUBBLE` overrides; the ZeRO-0
cond path is CI-exercised by tests/test_pipeline.py).

Run:   ZERO=1 SKIP=1 python tools/repro_cond_ppermute_deadlock.py  # deadlock
       ZERO=0 SKIP=1 python tools/repro_cond_ppermute_deadlock.py  # OK
       ZERO=1 SKIP=0 python tools/repro_cond_ppermute_deadlock.py  # OK
"""

import os

os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")

import jax

jax.config.update("jax_platforms", "cpu")

import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax.numpy as jnp

import deepspeed_tpu.parallel.pipe.pipeline as pl

SKIP = os.environ.get("SKIP", "1") == "1"
ZERO = int(os.environ.get("ZERO", "1"))
pl.default_skip_bubble = lambda: SKIP

from deepspeed_tpu.config.config import DeepSpeedTPUConfig
from deepspeed_tpu.models.gpt import GPTConfig
from deepspeed_tpu.parallel.mesh import build_mesh
from deepspeed_tpu.parallel.pipe import PipelineEngine, gpt_pipe_model


def main():
    cfg = GPTConfig(vocab_size=128, max_seq_len=32, hidden_size=32,
                    num_layers=4, num_heads=2, dropout_rate=0.0,
                    dtype=jnp.float32)
    eng = PipelineEngine(gpt_pipe_model(cfg), DeepSpeedTPUConfig({
        "train_micro_batch_size_per_gpu": 2,
        "gradient_accumulation_steps": 4,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": ZERO}}),
        mesh=build_mesh(data=4, pipe=2))
    rng = np.random.default_rng(0)
    b = {"input_ids": rng.integers(0, 128, (4, 4, 32), dtype=np.int32)}
    losses = [float(eng.train_batch(b)) for _ in range(3)]
    print(f"OK zero={ZERO} skip={SKIP}", [round(l, 4) for l in losses])


if __name__ == "__main__":
    main()
