"""Sparse-kernel block-size sweep at seq 4096 vs dense flash (fwd+bwd,
8-layer stacks, in-run A/B)."""
import functools, sys, time
import jax, jax.numpy as jnp, numpy as np
sys.path.insert(0, "/root/repo")
from deepspeed_tpu.ops.sparse_attention import (BigBirdSparsityConfig,
                                               sparse_attention)
from deepspeed_tpu.ops.transformer.flash_attention import flash_attention

LAYERS, B, H, D, S = 8, 2, 12, 64, 4096

def timed(fn, q, steps=8, warmup=2):
    grad = jax.jit(jax.grad(lambda q: jnp.sum(fn(q).astype(jnp.float32))))
    for _ in range(warmup):
        g = grad(q)
    float(jnp.sum(g.astype(jnp.float32)))
    t0 = time.perf_counter()
    for _ in range(steps):
        g = grad(q)
    float(jnp.sum(g.astype(jnp.float32)))
    return (time.perf_counter() - t0) / steps * 1e3

def main():
    print("platform:", jax.devices()[0].platform, flush=True)
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((B, S, H, D)), jnp.bfloat16) * 0.1

    def stack(q, one):
        x = q
        for _ in range(LAYERS):
            x = one(x)
        return x

    t = timed(lambda x: stack(x, lambda y: flash_attention(
        y, y, y, causal=True)), q)
    print(f"dense flash      : {t:7.1f} ms", flush=True)
    for blk in (64, 128, 256, 512):
        sc = BigBirdSparsityConfig(num_heads=H, block=blk,
                                   num_random_blocks=1,
                                   num_sliding_window_blocks=3,
                                   num_global_blocks=1,
                                   attention="unidirectional")
        layout = sc.make_layout(S)
        dens = layout.sum() / layout.size
        t = timed(lambda x: stack(x, lambda y: sparse_attention(
            y, y, y, layout, blk, causal=True, impl="pallas")), q)
        print(f"bigbird blk {blk:4d}: {t:7.1f} ms (density {dens:.2%})",
              flush=True)

main()
