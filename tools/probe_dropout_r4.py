"""Dropout-regime A/B (r3 VERDICT task 2): GPT-2 bench config with
dropout 0.1 — threefry nn.Dropout vs counter-hash dropout
(ops/dropout.py) vs dropout-off, one process."""
import sys, time
import jax
import numpy as np
sys.path.insert(0, "/root/repo")


def run(name, dropout_rate, fast, steps=8, windows=2):
    import deepspeed_tpu
    from deepspeed_tpu.models import make_gpt

    model, cfg = make_gpt("gpt2", dropout_rate=dropout_rate, remat=False,
                          max_seq_len=512, fast_dropout=fast)
    rng = np.random.default_rng(0)
    micro_bs, seq, gas = 16, 512, 8
    batches = {"input_ids": rng.integers(0, cfg.vocab_size,
                                         (gas, micro_bs, seq),
                                         dtype=np.int32)}
    one = jax.tree_util.tree_map(lambda x: x[0], batches)
    params = model.init({"params": jax.random.PRNGKey(0),
                         "dropout": jax.random.PRNGKey(1)}, one)["params"]
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, params=params,
        config={"train_micro_batch_size_per_gpu": micro_bs,
                "gradient_accumulation_steps": gas,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
                "zero_optimization": {"stage": 2},
                "data_types": {"grad_accum_dtype": "bfloat16"},
                "bf16": {"enabled": True}})
    for _ in range(2):
        loss = engine.train_batch(batches)
    _ = float(loss)
    best = float("inf")
    for _ in range(windows):
        t0 = time.perf_counter()
        for _ in range(steps):
            loss = engine.train_batch(batches)
        _ = float(loss)
        best = min(best, time.perf_counter() - t0)
    tps = gas * micro_bs * seq * steps / best
    print(f"[{name}] {tps:,.0f} tok/s (loss {float(loss):.3f})", flush=True)
    return tps


def main():
    print("platform:", jax.devices()[0].platform, flush=True)
    off = run("dropout off       ", 0.0, False)
    slow = run("dropout threefry  ", 0.1, False)
    fast = run("dropout hash      ", 0.1, True)
    print(f"threefry {slow/off:.1%} of off; hash {fast/off:.1%} of off "
          f"(hash vs threefry {fast/slow - 1:+.1%})", flush=True)


if __name__ == "__main__":
    main()
