#!/usr/bin/env python
"""Merge a run's per-attempt goodput manifests + metrics into ONE report.

The run-level answer to "of N hours of wall-clock, what fraction trained,
what was lost to which cause, and at what MFU?" — the artifact a fleet
operator (and this repo's perf PRs) cite for unattended runs that
restarted. Feed it the run dir (the job's ``telemetry.dir``, where the
engine writes ``run_manifest.aNNNN.<host>.json`` and ``metrics.jsonl``;
docs/OBSERVABILITY.md "Goodput accounting"):

    python tools/goodput_report.py /runs/exp17/telemetry
    python tools/goodput_report.py /runs/exp17/telemetry --json
    python tools/goodput_report.py --selftest

What the merge adds over any single attempt's numbers:

- **inter-attempt downtime** — the gap between one attempt's death and the
  next attempt's spawn (supervisor backoff + scheduling) becomes a
  ``restart`` row instead of invisible time;
- **cross-attempt replay** — steps the resumed attempt re-earned below the
  previous attempt's high-water mark are reclassified from
  productive_step to rollback_replay (the engine can't know; the merge
  can, from first_step/steps_committed in adjacent manifests);
- **unaccounted** — wall-clock the dead attempt never got to attribute
  (death after its last manifest refresh), reported honestly as its own
  row rather than silently inflating a category.

Standalone on purpose: stdlib only, so it runs anywhere the run dir lands
(including hosts without jax installed).
"""

import argparse
import json
import os
import sys
import tempfile
from typing import Any, Dict, List, Optional

MANIFEST_PREFIX = "run_manifest."
DEFAULT_METRICS_FILE = "metrics.jsonl"

# Keep in sync with deepspeed_tpu/telemetry/goodput.py CATEGORIES (this
# tool is import-free by design; the doc-lint test pins the doc tables to
# the package's list).
CATEGORIES = (
    "productive_step",
    "ckpt_snapshot",
    "ckpt_write_stall",
    "rollback_restore",
    "rollback_replay",
    "data_stall",
    "recompile",
    "init_restore",
    "elastic_reshard",
    "autotune_search",
    "idle_other",
)


# ---------------------------------------------------------------------------
# Loading
# ---------------------------------------------------------------------------

def load_manifests(run_dir: str) -> List[Dict[str, Any]]:
    out = []
    for name in sorted(os.listdir(run_dir)):
        if not (name.startswith(MANIFEST_PREFIX) and name.endswith(".json")):
            continue
        path = os.path.join(run_dir, name)
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError) as e:
            print(f"[goodput_report] skipping unreadable {name}: {e}",
                  file=sys.stderr)
            continue
        doc["_file"] = name
        out.append(doc)
    return out


def load_goodput_metrics(run_dir: str, metrics_file: str) -> Dict[Any, float]:
    """Last value per (attempt, tag) for goodput/* and engine/mfu rows —
    the gauges are cumulative, so last-write-wins is the freshest total.
    Multi-host runs host-scope the filename (``metrics.<host>.jsonl``);
    every matching file is read."""
    import glob as _glob
    root, ext = os.path.splitext(metrics_file)
    paths = sorted(set(
        _glob.glob(os.path.join(run_dir, metrics_file))
        + _glob.glob(os.path.join(run_dir, f"{root}.*{ext}"))))
    latest: Dict[Any, float] = {}
    for path in paths:
        _load_one_metrics_file(path, latest)
    return latest


def _load_one_metrics_file(path: str, latest: Dict[Any, float]) -> None:
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                row = json.loads(line)
            except ValueError:
                continue  # torn final line of a killed attempt
            tag = row.get("tag", "")
            if not (tag.startswith("goodput/") or tag == "engine/mfu"):
                continue
            attempt = int(row.get("attempt", 0))
            latest[(attempt, tag)] = float(row.get("value", 0.0))


# ---------------------------------------------------------------------------
# Merging
# ---------------------------------------------------------------------------

def _merge_attempt(manifests: List[Dict[str, Any]],
                   metrics: Dict[Any, float]) -> Dict[str, Any]:
    """Collapse one attempt's per-host manifests (averaging categories
    across hosts — they describe the same wall-clock interval) and refresh
    each category with the metrics stream when that is newer (both are
    cumulative; max = freshest)."""
    attempt = int(manifests[0].get("attempt", 0))
    n = len(manifests)
    cats = {c: 0.0 for c in CATEGORIES}
    for m in manifests:
        for c, v in (m.get("categories") or {}).items():
            cats[c] = cats.get(c, 0.0) + float(v or 0.0)
    cats = {c: v / n for c, v in cats.items()}
    for c in CATEGORIES:
        mv = metrics.get((attempt, f"goodput/{c}_sec"))
        if mv is not None:
            cats[c] = max(cats[c], mv)
    # Auxiliary sub-attributions (goodput aux gauges: pipe_bubble_sec,
    # exposed_comm_sec, straggler_sec, ...): cumulative like the
    # categories but OVERLAPPING productive_step, so they merge into
    # their own table. Manifest "aux" + any non-category goodput/* metric
    # row; max = freshest.
    aux: Dict[str, float] = {}
    for m in manifests:
        for k, v in (m.get("aux") or {}).items():
            aux[k] = max(aux.get(k, 0.0), float(v or 0.0))
    non_aux = {f"{c}_sec" for c in CATEGORIES} | {
        "wall_sec", "goodput_frac", "steps_committed"}
    for (att, tag), v in metrics.items():
        if att != attempt or not tag.startswith("goodput/"):
            continue
        name = tag[len("goodput/"):]
        if name not in non_aux:
            aux[name] = max(aux.get(name, 0.0), float(v))
    starts = [m.get("start_wall") for m in manifests
              if m.get("start_wall") is not None]
    ends = [m.get("end_wall") for m in manifests
            if m.get("end_wall") is not None]
    start_wall = min(starts) if starts else None
    end_wall = max(ends) if ends else None
    wall = max((float(m.get("wall_sec") or 0.0) for m in manifests),
               default=0.0)
    wall = max(wall, metrics.get((attempt, "goodput/wall_sec"), 0.0))
    if start_wall is not None and end_wall is not None:
        wall = max(wall, end_wall - start_wall)
    mfus = [m.get("mfu") for m in manifests if m.get("mfu") is not None]
    mfu = metrics.get((attempt, "engine/mfu"),
                      sum(mfus) / len(mfus) if mfus else None)
    step_times = [m.get("mean_step_time_sec") for m in manifests
                  if m.get("mean_step_time_sec") is not None]
    first_steps = [m.get("first_step") for m in manifests
                   if m.get("first_step") is not None]
    rcs = [m.get("exit_rc") for m in manifests if m.get("exit_rc") is not None]
    causes = [m.get("restart_cause") for m in manifests
              if m.get("restart_cause")]
    # Live-elasticity world-change timeline (resilience/elastic.py):
    # union across host manifests, deduplicated by epoch, step-ordered —
    # rendered as a per-attempt timeline row so reshard time is
    # attributable (its seconds live in the elastic_reshard category).
    elastic: Dict[int, Dict[str, Any]] = {}
    for m in manifests:
        for entry in (m.get("elastic") or []):
            elastic.setdefault(int(entry.get("epoch", 0)), entry)
    evictions: List[Dict[str, Any]] = []
    seen_ev = set()
    for m in manifests:
        for entry in (m.get("eviction_decisions") or []):
            key = (entry.get("host"), entry.get("step"),
                   entry.get("source"))
            if key not in seen_ev:
                seen_ev.add(key)
                evictions.append(entry)
    return {
        "attempt": attempt,
        "hosts": sorted({m.get("host", "?") for m in manifests}),
        "run_id": manifests[0].get("run_id"),
        "config_hash": manifests[0].get("config_hash"),
        "start_wall": start_wall,
        "end_wall": end_wall,
        "wall_sec": wall,
        "categories": cats,
        "aux": aux,
        "first_step": min(first_steps) if first_steps else None,
        "steps_committed": max((int(m.get("steps_committed") or 0)
                                for m in manifests), default=0),
        "mean_step_time_sec": (sum(step_times) / len(step_times)
                               if step_times else None),
        "mfu": mfu,
        "exit_rc": rcs[0] if rcs else None,
        "restart_cause": causes[0] if causes else None,
        "elastic": [elastic[e] for e in sorted(elastic)],
        "eviction_decisions": evictions,
    }


def merge_run(run_dir: str,
              metrics_file: str = DEFAULT_METRICS_FILE) -> Dict[str, Any]:
    """The cross-attempt merge. Returns the full report dict (the --json
    output)."""
    manifests = load_manifests(run_dir)
    if not manifests:
        raise FileNotFoundError(
            f"no {MANIFEST_PREFIX}*.json manifests under {run_dir} — is "
            "this the job's telemetry.dir, with telemetry.goodput on?")
    metrics = load_goodput_metrics(run_dir, metrics_file)
    by_attempt: Dict[int, List[Dict[str, Any]]] = {}
    for m in manifests:
        by_attempt.setdefault(int(m.get("attempt", 0)), []).append(m)
    attempts = [_merge_attempt(by_attempt[a], metrics)
                for a in sorted(by_attempt)]

    # Cross-attempt replay: steps a resumed attempt re-earned at or below
    # the previous attempt's high-water mark were booked productive by an
    # engine that couldn't know better — reclassify their estimated time.
    for prev, cur in zip(attempts, attempts[1:]):
        if cur["first_step"] is None or cur["mean_step_time_sec"] is None:
            continue
        replay_steps = prev["steps_committed"] - (cur["first_step"] - 1)
        if replay_steps <= 0:
            continue
        replay_sec = min(replay_steps * cur["mean_step_time_sec"],
                         cur["categories"].get("productive_step", 0.0))
        cur["categories"]["productive_step"] -= replay_sec
        cur["categories"]["rollback_replay"] = \
            cur["categories"].get("rollback_replay", 0.0) + replay_sec
        cur["replay_steps"] = replay_steps

    # Inter-attempt downtime: death -> next spawn (backoff + scheduling).
    restart_sec = 0.0
    for prev, cur in zip(attempts, attempts[1:]):
        if prev["end_wall"] is not None and cur["start_wall"] is not None:
            restart_sec += max(0.0, cur["start_wall"] - prev["end_wall"])

    totals = {c: sum(a["categories"].get(c, 0.0) for a in attempts)
              for c in CATEGORIES}
    aux_keys = sorted({k for a in attempts for k in a.get("aux", {})})
    sub_attributions = {k: sum(a.get("aux", {}).get(k, 0.0)
                               for a in attempts) for k in aux_keys}
    attempt_wall = sum(a["wall_sec"] for a in attempts)
    starts = [a["start_wall"] for a in attempts
              if a["start_wall"] is not None]
    ends = [(a["end_wall"] if a["end_wall"] is not None
             else (a["start_wall"] + a["wall_sec"]
                   if a["start_wall"] is not None else None))
            for a in attempts]
    ends = [e for e in ends if e is not None]
    if starts and ends:
        run_wall = max(ends) - min(starts)
    else:
        run_wall = attempt_wall + restart_sec
    # Wall-clock an attempt lived but never attributed (death after its
    # last manifest refresh) — honesty row, never folded into a category.
    unaccounted = max(0.0, run_wall - restart_sec
                      - sum(totals.values()))
    attributed = ((sum(totals.values()) + restart_sec) / run_wall
                  if run_wall > 0 else 1.0)

    productive = totals.get("productive_step", 0.0)
    weights = [(a["categories"].get("productive_step", 0.0), a["mfu"])
               for a in attempts if a["mfu"] is not None]
    wsum = sum(w for w, _ in weights)
    mfu = (sum(w * m for w, m in weights) / wsum if wsum > 0
           else (weights[-1][1] if weights else None))

    return {
        "run_dir": os.path.abspath(run_dir),
        "run_id": attempts[0].get("run_id"),
        "config_hash": attempts[0].get("config_hash"),
        "attempts": attempts,
        "n_attempts": len(attempts),
        "n_restarts": len(attempts) - 1,
        "wall_sec": run_wall,
        "categories": totals,
        "sub_attributions": sub_attributions,
        "restart_sec": restart_sec,
        "unaccounted_sec": unaccounted,
        "attributed_frac": attributed,
        "goodput_frac": (productive / run_wall) if run_wall > 0 else 0.0,
        "mfu": mfu,
        "steps_committed": max((a["steps_committed"] for a in attempts),
                               default=0),
    }


# ---------------------------------------------------------------------------
# Rendering
# ---------------------------------------------------------------------------

def render(report: Dict[str, Any]) -> str:
    out = []
    wall = report["wall_sec"] or 1.0
    mfu = report["mfu"]
    out.append(f"goodput report — run {report.get('run_id')} "
               f"({report['run_dir']})")
    out.append(
        f"attempts: {report['n_attempts']}   "
        f"wall-clock: {report['wall_sec']:.1f} s   "
        f"steps: {report['steps_committed']}   "
        f"goodput: {report['goodput_frac']:.1%}   "
        f"MFU: {mfu:.1%}   " if mfu is not None else
        f"attempts: {report['n_attempts']}   "
        f"wall-clock: {report['wall_sec']:.1f} s   "
        f"steps: {report['steps_committed']}   "
        f"goodput: {report['goodput_frac']:.1%}   MFU: n/a   ")
    out[-1] += f"attributed: {report['attributed_frac']:.1%}"
    out.append("")
    hdr = f"{'category':<20} {'seconds':>12} {'share':>8}"
    out.append(hdr)
    out.append("-" * len(hdr))
    rows = sorted(report["categories"].items(), key=lambda kv: -kv[1])
    rows.append(("restart", report["restart_sec"]))
    rows.append(("unaccounted", report["unaccounted_sec"]))
    for name, sec in rows:
        out.append(f"{name:<20} {sec:>12.3f} {sec / wall:>7.1%}")
    subs = {k: v for k, v in (report.get("sub_attributions") or {}).items()
            if v > 0.0}
    if subs:
        # Overlap productive_step (pipe bubbles, exposed collectives,
        # straggler wait) — the time the ROADMAP overlap/elasticity work
        # claws back; NOT part of the wall-clock partition above.
        out.append("")
        out.append("sub-attributions (inside productive_step):")
        for name, sec in sorted(subs.items(), key=lambda kv: -kv[1]):
            out.append(f"  {name:<18} {sec:>12.3f} {sec / wall:>7.1%}")
    out.append("")
    out.append("restarts:")
    hdr = (f"  {'attempt':>7} {'rc':>5} {'cause':<17} {'steps':>6} "
           f"{'wall s':>9} {'goodput':>8} {'mfu':>7}")
    out.append(hdr)
    out.append("  " + "-" * (len(hdr) - 2))
    for a in report["attempts"]:
        aw = a["wall_sec"] or 1.0
        gp = a["categories"].get("productive_step", 0.0) / aw
        m = f"{a['mfu']:.1%}" if a["mfu"] is not None else "n/a"
        rc = a["exit_rc"] if a["exit_rc"] is not None else "?"
        out.append(f"  {a['attempt']:>7} {rc!s:>5} "
                   f"{(a['restart_cause'] or '?'):<17} "
                   f"{a['steps_committed']:>6} {a['wall_sec']:>9.1f} "
                   f"{gp:>7.1%} {m:>7}")
        # Live-elasticity timeline: one row per attempt that changed
        # worlds, so in-process reshards are visible next to the restart
        # they avoided (their seconds live in elastic_reshard above,
        # never idle_other).
        for e in (a.get("elastic") or []):
            out.append(
                f"          world -> {e.get('world_size')} "
                f"({e.get('cause', '?')} @ step {e.get('step', '?')}, "
                f"epoch {e.get('epoch', '?')}, "
                f"{float(e.get('reshard_sec') or 0.0):.2f}s in-process "
                f"reshard)")
        for d in (a.get("eviction_decisions") or []):
            out.append(
                f"          eviction[{d.get('source', 'engine')}] "
                f"host={d.get('host')} z={d.get('zscore')} "
                f"gain={float(d.get('projected_gain_sec') or 0.0):.1f}s "
                f"cost={float(d.get('reshard_cost_sec') or 0.0):.1f}s -> "
                f"{'EVICT' if d.get('evict') else 'keep'}")
    return "\n".join(out)


# ---------------------------------------------------------------------------
# Selftest
# ---------------------------------------------------------------------------

def _write(path: str, doc: Any) -> None:
    with open(path, "w") as f:
        json.dump(doc, f)


def _selftest() -> int:
    """Synthesize the 2-attempt run dir the e2e test produces for real
    (SIGTERM mid-run, supervisor auto-resume), merge it, and assert the
    invariants the report is trusted for: category totals sum to run
    wall-clock within tolerance, goodput < 1 with nonzero restart /
    init_restore / replay attribution, and MFU carried through."""
    with tempfile.TemporaryDirectory() as td:
        # Attempt 0: SIGTERM'd after step 30 — atexit never ran, so
        # end_wall/exit_rc came from the supervisor stamp; its last
        # manifest refresh attributed 60 of its 62 lived seconds.
        _write(os.path.join(td, "run_manifest.a0000.hostA.json"), {
            "format": 1, "run_id": "cafe01", "attempt": 0, "host": "hostA",
            "config_hash": "deadbeef0123",
            "start_wall": 1000.0, "end_wall": 1062.0, "wall_sec": 62.0,
            "exit_rc": -15, "restart_cause": "preemption",
            "categories": {"productive_step": 40.0, "data_stall": 4.0,
                           "recompile": 8.0, "ckpt_snapshot": 2.0,
                           "init_restore": 5.0, "elastic_reshard": 0.5,
                           "idle_other": 0.5},
            "aux": {"exposed_comm_sec": 6.0, "straggler_sec": 2.0},
            # Live elasticity: one in-process shrink at step 20 (its 0.5s
            # lives in elastic_reshard above, NOT idle_other) and one
            # declined eviction decision.
            "elastic": [{"epoch": 1, "step": 20, "world_size": 4,
                         "cause": "preemption", "reshard_sec": 0.5}],
            "eviction_decisions": [
                {"host": "hostB", "zscore": 4.2, "evict": False,
                 "projected_gain_sec": 30.0, "reshard_cost_sec": 60.0,
                 "min_gain_factor": 2.0, "step": 25, "source": "engine"}],
            "first_step": 1, "steps_committed": 30,
            "mean_step_time_sec": 1.0, "mfu": 0.30, "n_chips": 8})
        # Attempt 1: spawned 2 s later (backoff), restored step 25,
        # re-earned 26..30 (replay), ran clean to step 60.
        _write(os.path.join(td, "run_manifest.a0001.hostA.json"), {
            "format": 1, "run_id": "cafe01", "attempt": 1, "host": "hostA",
            "config_hash": "deadbeef0123",
            "start_wall": 1064.0, "end_wall": 1130.0, "wall_sec": 66.0,
            "exit_rc": 0, "restart_cause": "clean",
            "categories": {"productive_step": 44.0, "data_stall": 3.0,
                           "recompile": 6.0, "ckpt_snapshot": 2.0,
                           "init_restore": 10.0, "idle_other": 1.0},
            "aux": {"exposed_comm_sec": 7.0},
            "first_step": 26, "steps_committed": 60,
            "mean_step_time_sec": 1.0, "mfu": 0.34, "n_chips": 8})
        with open(os.path.join(td, DEFAULT_METRICS_FILE), "w") as f:
            f.write(json.dumps({"tag": "engine/mfu", "value": 0.34,
                                "step": 60, "kind": "gauge",
                                "attempt": 1}) + "\n")
            # torn final line from the SIGTERM — must be tolerated
            f.write('{"tag": "goodput/wall_se')

        report = merge_run(td)
        text = render(report)

    assert report["n_attempts"] == 2 and report["n_restarts"] == 1
    # run wall = 1130 - 1000
    assert abs(report["wall_sec"] - 130.0) < 1e-6
    # restart gap = 1064 - 1062
    assert abs(report["restart_sec"] - 2.0) < 1e-6, report["restart_sec"]
    # replay: attempt 1 re-earned steps 26..30 at 1 s/step
    a1 = report["attempts"][1]
    assert a1.get("replay_steps") == 5
    assert abs(report["categories"]["rollback_replay"] - 5.0) < 1e-6
    assert abs(report["categories"]["productive_step"] - (40 + 44 - 5)) < 1e-6
    # category totals (+restart +unaccounted) sum to run wall-clock
    total = (sum(report["categories"].values()) + report["restart_sec"]
             + report["unaccounted_sec"])
    assert abs(total - report["wall_sec"]) / report["wall_sec"] < 0.05, total
    assert report["attributed_frac"] > 0.95
    assert 0.0 < report["goodput_frac"] < 1.0
    assert report["categories"]["init_restore"] == 15.0
    # sub-attributions: summed across attempts, rendered in their own
    # overlap table (never part of the wall partition)
    assert report["sub_attributions"]["exposed_comm_sec"] == 13.0
    assert report["sub_attributions"]["straggler_sec"] == 2.0
    assert "sub-attributions" in text and "exposed_comm_sec" in text
    # Live elasticity: reshard seconds land in their own category (never
    # idle_other) and the world-change timeline + eviction decision rows
    # render under the attempt that produced them.
    assert report["categories"]["elastic_reshard"] == 0.5
    a0 = report["attempts"][0]
    assert a0["elastic"][0]["world_size"] == 4
    assert a0["eviction_decisions"][0]["host"] == "hostB"
    assert "world -> 4 (preemption @ step 20" in text
    assert "eviction[engine] host=hostB" in text and "keep" in text
    # MFU: productive-time-weighted over both attempts, in (0.30, 0.34)
    assert 0.30 < report["mfu"] < 0.34, report["mfu"]
    assert "restarts:" in text and "preemption" in text
    print(text)
    print("\nselftest ok")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("run_dir", nargs="?",
                    help="the job's telemetry.dir (run manifests + "
                         "metrics.jsonl)")
    ap.add_argument("--metrics", default=DEFAULT_METRICS_FILE,
                    help="metrics JSONL filename inside the run dir")
    ap.add_argument("--json", action="store_true",
                    help="emit the merged report as JSON")
    ap.add_argument("--selftest", action="store_true",
                    help="run the built-in 2-attempt round-trip check")
    args = ap.parse_args(argv)
    if args.selftest:
        return _selftest()
    if not args.run_dir:
        ap.error("run dir required (or --selftest)")
    report = merge_run(args.run_dir, metrics_file=args.metrics)
    if args.json:
        print(json.dumps(report, indent=1))
    else:
        print(render(report))
    return 0


if __name__ == "__main__":
    sys.exit(main())
