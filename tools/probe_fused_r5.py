"""Round-5 fused LN+projection A/B on the real chip, bench config, one
process (the tunnel drifts ±10-12% between runs — only in-run comparisons
count). Variants: unfused baseline, fused_ln at both pre-LN sites,
each with dropout off and on (0.1)."""

import sys
import time

import jax
import numpy as np

sys.path.insert(0, "/root/repo")

PEAK = 197.0


def run_variant(name, steps=8, windows=2, dropout_rate=0.0, **overrides):
    import deepspeed_tpu
    from deepspeed_tpu.models import make_gpt

    model, cfg = make_gpt("gpt2", dropout_rate=dropout_rate, remat=False,
                          max_seq_len=512, **overrides)
    rng = np.random.default_rng(0)
    micro_bs, seq, gas = 16, 512, 8
    batches = {"input_ids": rng.integers(0, cfg.vocab_size,
                                         (gas, micro_bs, seq),
                                         dtype=np.int32)}
    one = jax.tree_util.tree_map(lambda x: x[0], batches)
    params = model.init({"params": jax.random.PRNGKey(0),
                         "dropout": jax.random.PRNGKey(1)}, one)["params"]
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, params=params,
        config={
            "train_micro_batch_size_per_gpu": micro_bs,
            "gradient_accumulation_steps": gas,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
            "zero_optimization": {"stage": 2},
            "data_types": {"grad_accum_dtype": "bfloat16"},
            "bf16": {"enabled": True},
        })
    for _ in range(2):
        loss = engine.train_batch(batches)
    _ = float(loss)
    best = float("inf")
    for _ in range(windows):
        t0 = time.perf_counter()
        for _ in range(steps):
            loss = engine.train_batch(batches)
        _ = float(loss)   # scalar fetch = tunnel fence
        best = min(best, time.perf_counter() - t0)
    tokens = gas * micro_bs * seq * steps
    tps = tokens / best
    n_params = sum(int(np.prod(x.shape))
                   for x in jax.tree_util.tree_leaves(params))
    flops = (6.0 * n_params + 12 * 12 * 768 * 512) * tokens
    mfu = flops / best / 1e12 / PEAK
    print(f"[{name}] {tps:,.0f} tok/s  MFU {mfu:.1%}  "
          f"(loss {float(loss):.3f})", flush=True)
    del engine
    return tps


def main():
    print("platform:", jax.devices()[0].platform, flush=True)
    base = run_variant("base     off", fused_ln=False)
    qkv = run_variant("qkv-only off", fused_ln="qkv")
    mlp = run_variant("mlp-only off", fused_ln="mlp")
    fused = run_variant("fused    off", fused_ln=True)
    print(f"qkv/base: {qkv / base:.3f}  mlp/base: {mlp / base:.3f}  "
          f"both/base: {fused / base:.3f}")


if __name__ == "__main__":
    main()
