"""Round-4 bench-config sweep: GPT-2 gas/micro-batch, one process A/B."""
import sys, time
import jax
import numpy as np
sys.path.insert(0, "/root/repo")


def run(name, micro_bs, gas, steps=8, windows=2):
    import deepspeed_tpu
    from deepspeed_tpu.models import make_gpt

    model, cfg = make_gpt("gpt2", dropout_rate=0.0, remat=False,
                          max_seq_len=512)
    rng = np.random.default_rng(0)
    seq = 512
    batches = {"input_ids": rng.integers(0, cfg.vocab_size,
                                         (gas, micro_bs, seq),
                                         dtype=np.int32)}
    one = jax.tree_util.tree_map(lambda x: x[0], batches)
    params = model.init({"params": jax.random.PRNGKey(0),
                         "dropout": jax.random.PRNGKey(1)}, one)["params"]
    try:
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=model, params=params,
            config={"train_micro_batch_size_per_gpu": micro_bs,
                    "gradient_accumulation_steps": gas,
                    "optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
                    "zero_optimization": {"stage": 2},
                    "data_types": {"grad_accum_dtype": "bfloat16"},
                    "bf16": {"enabled": True}})
        for _ in range(2):
            loss = engine.train_batch(batches)
        _ = float(loss)
        best = float("inf")
        for _ in range(windows):
            t0 = time.perf_counter()
            for _ in range(steps):
                loss = engine.train_batch(batches)
            _ = float(loss)
            best = min(best, time.perf_counter() - t0)
        tps = gas * micro_bs * seq * steps / best
        print(f"[{name}] {tps:,.0f} tok/s", flush=True)
        return tps
    except Exception as e:
        print(f"[{name}] FAILED: {type(e).__name__} {str(e)[:80]}",
              flush=True)
        return 0.0


def main():
    print("platform:", jax.devices()[0].platform, flush=True)
    run("mb16 gas8  (bench)", 16, 8)
    run("mb16 gas16       ", 16, 16, steps=4)
    run("mb24 gas8        ", 24, 8)
    run("mb32 gas8        ", 32, 8)
    run("mb8  gas16       ", 8, 16, steps=4)


if __name__ == "__main__":
    main()
