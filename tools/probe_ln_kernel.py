"""Micro A/B: ln_matmul Pallas kernel vs XLA's unfused LN+matmul, fwd-only
and fwd+bwd, 12-iteration loops amortizing dispatch (one process, real
chip). Locates where the end-to-end deficit (probe_fused_r5: 0.90x) lives."""

import sys
import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, "/root/repo")

from deepspeed_tpu.ops.transformer.fused import ln_matmul, ln_matmul_reference


def bench(name, fn, *args, steps=30):
    f = jax.jit(fn)
    out = f(*args)
    _ = float(jnp.sum(jax.tree_util.tree_leaves(out)[0]).astype(jnp.float32))
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(steps):
            out = f(*args)
        _ = float(jnp.sum(
            jax.tree_util.tree_leaves(out)[0]).astype(jnp.float32))
        best = min(best, (time.perf_counter() - t0) / steps)
    print(f"[{name}] {best * 1e3:.3f} ms", flush=True)
    return best


def main(n=8192, d=768, f=2304, act=None, layers=12):
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((n, d)), jnp.bfloat16)
    gamma = jnp.ones(d, jnp.float32)
    beta = jnp.zeros(d, jnp.float32)
    ws = jnp.asarray(rng.standard_normal((layers, d, f)) / np.sqrt(d),
                     jnp.bfloat16)
    bias = jnp.zeros(f, jnp.bfloat16)
    proj = jnp.asarray(rng.standard_normal((layers, f, d)) / np.sqrt(f),
                       jnp.bfloat16)
    print(f"== n={n} d={d} f={f} act={act} x{layers}", flush=True)

    def stack(op):
        # layers x (ln+matmul -> proj back to d) so shapes chain.
        def run(x, ws, proj):
            def body(h, wp):
                w, p = wp
                y = op(h, gamma, beta, w, bias)
                return jnp.dot(y, p, preferred_element_type=jnp.float32
                               ).astype(h.dtype), None
            h, _ = jax.lax.scan(body, x, (ws, proj))
            return h
        return run

    fused = stack(partial(ln_matmul, activation=act))
    ref = stack(partial(ln_matmul_reference, activation=act))

    bench("fwd  fused", fused, x, ws, proj)
    bench("fwd  xla  ", ref, x, ws, proj)

    def grad_of(run):
        def loss(x, ws, proj):
            return jnp.sum(run(x, ws, proj).astype(jnp.float32))
        return jax.grad(loss, argnums=(0, 1))

    bench("f+b  fused", grad_of(fused), x, ws, proj)
    bench("f+b  xla  ", grad_of(ref), x, ws, proj)


if __name__ == "__main__":
    print("platform:", jax.devices()[0].platform, flush=True)
    main(act=None)
    main(f=3072, act="gelu")
