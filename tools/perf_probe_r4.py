"""Round-4 GPT-2 lever A/B on the real chip: vocab padding (50257->50304)
and the one-hot-matmul embedding gradient, alone and combined, against the
round-3 bench config — all variants in ONE process (the tunnel's ±10-12%
run-to-run drift makes cross-run comparison meaningless)."""

import sys
import time

import jax
import numpy as np

sys.path.insert(0, "/root/repo")


def run_variant(name, steps=8, windows=2, **overrides):
    import deepspeed_tpu
    from deepspeed_tpu.models import make_gpt

    model, cfg = make_gpt("gpt2", dropout_rate=0.0, remat=False,
                          max_seq_len=512, **overrides)
    rng = np.random.default_rng(0)
    micro_bs, seq, gas = 16, 512, 8
    batches = {"input_ids": rng.integers(0, cfg.vocab_size,
                                         (gas, micro_bs, seq),
                                         dtype=np.int32)}
    one = jax.tree_util.tree_map(lambda x: x[0], batches)
    params = model.init({"params": jax.random.PRNGKey(0),
                         "dropout": jax.random.PRNGKey(1)}, one)["params"]
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, params=params,
        config={
            "train_micro_batch_size_per_gpu": micro_bs,
            "gradient_accumulation_steps": gas,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
            "zero_optimization": {"stage": 2},
            "data_types": {"grad_accum_dtype": "bfloat16"},
            "bf16": {"enabled": True},
        })
    for _ in range(2):
        loss = engine.train_batch(batches)
    _ = float(loss)
    best = float("inf")
    for _ in range(windows):
        t0 = time.perf_counter()
        for _ in range(steps):
            loss = engine.train_batch(batches)
        _ = float(loss)   # scalar fetch = tunnel fence
        best = min(best, time.perf_counter() - t0)
    tokens = gas * micro_bs * seq * steps
    tps = tokens / best
    print(f"[{name}] {tps:,.0f} tok/s  (loss {float(loss):.3f})",
          flush=True)
    del engine
    return tps


def main():
    print("platform:", jax.devices()[0].platform, flush=True)
    base = run_variant("base          ")
    pad = run_variant("vocab_pad     ", vocab_pad_multiple=128)
    emb = run_variant("embed_matmul  ", embed_grad_matmul=True)
    both = run_variant("both          ", vocab_pad_multiple=128,
                       embed_grad_matmul=True)
    print(f"pad: {pad/base - 1:+.1%}  emb: {emb/base - 1:+.1%}  "
          f"both: {both/base - 1:+.1%} vs base", flush=True)


if __name__ == "__main__":
    main()
