"""Acceptance probe: the in-process elastic reshard beats a cold restart.

The whole point of live elasticity (resilience/elastic.py) is removing the
cold-restart bill — interpreter + jax import, engine construction, XLA
compile, checkpoint deserialize — that ``init_restore`` dominates in the
goodput reports. This probe measures both paths on the same tiny-MLP job
over a 2-slice virtual CPU mesh:

- **in-process**: a running 8-chip engine is told slice 1 is preempted
  (``ElasticCoordinator.request_shrink``); the measured cost is the
  coordinator's own ``elastic/reshard_sec`` (drain + state gather + mesh
  and step-fn rebuild + reshard + first-step recompile);
- **cold restart**: a fresh subprocess builds the 4-chip engine, resumes
  from the checkpoint the first engine committed, and runs one step — the
  wall clock of the whole subprocess, which is exactly what a supervisor
  restart pays (the interpreter/import tax included; that is the honest
  comparison).

Asserts the in-process path is cheaper (``--selftest`` — wired into
tier-1 via tests/test_elastic.py).

Run: JAX_PLATFORMS=cpu python tools/probe_elasticity.py [--selftest]
"""

import json
import os
import subprocess
import sys
import tempfile
import time

os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") + \
    " --xla_force_host_platform_device_count=8"
os.environ.setdefault("JAX_PLATFORMS", "cpu")
_ROOT = os.path.join(os.path.dirname(os.path.abspath(__file__)), os.pardir)
sys.path.insert(0, _ROOT)
sys.path.insert(0, os.path.join(_ROOT, "tests"))

GLOBAL_BATCH = 24
HIDDEN, LAYERS = 64, 2


def _config(ckpt_dir, live=True):
    cfg = {
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 1},
        "mesh": {"slices": 2},
        "steps_per_print": 10_000,
        "elasticity": {
            "enabled": True,
            "max_train_batch_size": GLOBAL_BATCH,
            "micro_batch_sizes": [1, 2],
            "min_chips": 1, "max_chips": 64, "version": 0.1,
        },
        "resilience": {
            "enabled": True,
            "checkpoint": {"dir": ckpt_dir, "interval": 1, "keep_last": 2,
                           "async": False},
        },
    }
    if live:
        cfg["elasticity"]["live"] = {"enabled": True, "grace_seconds": 60.0}
    return cfg


def _batches(engine, seed=7):
    import numpy as np
    rng = np.random.default_rng(seed)
    gas = engine.gradient_accumulation_steps
    return {
        "x": rng.standard_normal(
            (gas, GLOBAL_BATCH // gas, HIDDEN)).astype(np.float32),
        "y": rng.standard_normal(
            (gas, GLOBAL_BATCH // gas, 8)).astype(np.float32),
    }


# The cold-restart side, run as its OWN process: a supervisor restart pays
# interpreter + imports + engine build + restore + first-step compile, and
# so does this script. mesh.slices=1 (the surviving slice), world 4.
_COLD_SCRIPT = r"""
import json, os, sys, time
t0 = time.monotonic()
os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") + \
    " --xla_force_host_platform_device_count=4"
os.environ.setdefault("JAX_PLATFORMS", "cpu")
root, ckpt_dir, out = sys.argv[1], sys.argv[2], sys.argv[3]
sys.path.insert(0, root)
sys.path.insert(0, os.path.join(root, "tests"))
import numpy as np
import deepspeed_tpu
from simple_model import mlp_loss_fn, mlp_params
GLOBAL_BATCH, HIDDEN = 24, 64
engine, _, _, _ = deepspeed_tpu.initialize(
    loss_fn=mlp_loss_fn, params=mlp_params(hidden=HIDDEN, layers=2),
    config={
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 1},
        "mesh": {"slices": 1},
        "steps_per_print": 10_000,
        "elasticity": {"enabled": True, "max_train_batch_size": GLOBAL_BATCH,
                       "micro_batch_sizes": [1, 2], "min_chips": 1,
                       "max_chips": 64, "version": 0.1},
        "resilience": {"enabled": True,
                       "checkpoint": {"dir": ckpt_dir, "interval": 1}},
    }, rng_seed=0)
path, _ = engine.auto_resume()
assert path is not None, "cold restart found no checkpoint"
rng = np.random.default_rng(7)
gas = engine.gradient_accumulation_steps
batch = {
    "x": rng.standard_normal((gas, GLOBAL_BATCH // gas, HIDDEN)).astype(
        np.float32),
    "y": rng.standard_normal((gas, GLOBAL_BATCH // gas, 8)).astype(
        np.float32),
}
loss = float(engine.train_batch(batch))
engine.ckpt_manager.close()
with open(out, "w") as f:
    json.dump({"cold_restart_sec": time.monotonic() - t0,
               "restored": path is not None, "loss": loss,
               "world": engine.mesh.size,
               "global_steps": engine.global_steps}, f)
"""


def run_probe():
    import deepspeed_tpu
    from simple_model import mlp_loss_fn, mlp_params

    td = tempfile.mkdtemp(prefix="probe_elasticity_")
    ckpt_dir = os.path.join(td, "ckpt")
    engine, _, _, _ = deepspeed_tpu.initialize(
        loss_fn=mlp_loss_fn, params=mlp_params(hidden=HIDDEN, layers=LAYERS),
        config=_config(ckpt_dir), rng_seed=0)
    assert engine.elastic is not None and engine.mesh.size == 8

    # Warm steps: compile the 8-chip program and commit checkpoints the
    # cold path will restore from.
    for _ in range(3):
        engine.train_batch(_batches(engine))
    engine.ckpt_manager.wait()

    # In-process shrink: slice 1 preempted -> world 4, measured by the
    # coordinator (drain + gather + rebuild). The first post-shrink step
    # carries the recompile, so time it into the in-process bill too —
    # the cold path's one step likewise carries its compile.
    engine.elastic.request_shrink(1)
    t0 = time.monotonic()
    engine.train_batch(_batches(engine))
    in_process_total = time.monotonic() - t0
    assert engine.mesh.size == 4, engine.mesh.size
    reshard_sec = float(engine.elastic.last_reshard_sec)
    engine.train_batch(_batches(engine))          # steady-state sanity
    engine.ckpt_manager.close()

    # Cold restart of the same shrink: fresh process, world 4, restore.
    out = os.path.join(td, "cold.json")
    t0 = time.monotonic()
    proc = subprocess.run(
        [sys.executable, "-c", _COLD_SCRIPT, _ROOT, ckpt_dir, out],
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
        capture_output=True, text=True, timeout=600)
    cold_wall = time.monotonic() - t0
    if proc.returncode != 0:
        print(proc.stdout, proc.stderr, file=sys.stderr)
        raise RuntimeError(f"cold-restart subprocess rc={proc.returncode}")
    with open(out) as f:
        cold = json.load(f)

    result = {
        "in_process_reshard_sec": round(reshard_sec, 4),
        "in_process_total_sec": round(in_process_total, 4),
        "cold_restart_sec": round(cold["cold_restart_sec"], 4),
        "cold_restart_wall_sec": round(cold_wall, 4),
        "speedup": round(cold["cold_restart_sec"]
                         / max(in_process_total, 1e-9), 2),
        "cold_world": cold["world"],
    }
    return result


def main(argv=None) -> int:
    selftest = "--selftest" in (argv or sys.argv[1:])
    result = run_probe()
    print(f"{'path':<28} {'seconds':>10}")
    print("-" * 40)
    print(f"{'in-process reshard only':<28} "
          f"{result['in_process_reshard_sec']:>10.3f}")
    print(f"{'in-process (+ first step)':<28} "
          f"{result['in_process_total_sec']:>10.3f}")
    print(f"{'cold supervisor restart':<28} "
          f"{result['cold_restart_sec']:>10.3f}")
    print(f"\nspeedup (cold / in-process): {result['speedup']:.1f}x")
    print(json.dumps(result))
    if selftest:
        # The acceptance gate: the in-process path (including its
        # recompile) must beat the cold restart (whose bill is dominated
        # by interpreter + jax import + engine re-construction — the
        # init_restore the goodput reports flagged).
        assert result["in_process_total_sec"] < result["cold_restart_sec"], \
            result
        assert result["cold_world"] == 4, result
        print("selftest ok")
    return 0


if __name__ == "__main__":
    sys.exit(main())
