#!/usr/bin/env python3
"""Autotune search report — stdlib-only, like the other report tools.

Renders ``autotune_result*.json`` (autotuning/search.py) into the
per-candidate verdict table: status, projected HBM, modeled cost,
measured step time, and the prune/elimination reason — plus the adopted
config's knobs and, when a metrics JSONL sits beside the result, the
``autotune/*`` gauges the search emitted.

Usage:
  python tools/autotune_report.py <run_dir | autotune_result.json>
  python tools/autotune_report.py --selftest
"""

import glob
import json
import os
import sys
from typing import Any, Dict, List, Optional

# Tags this report reads — pinned against autotuning/search.py's
# AUTOTUNE_METRIC_TAGS by tests/test_doc_lint.py (this file is
# deliberately import-free of the package, the report-tool rule).
GAUGES = ("autotune/candidates", "autotune/pruned", "autotune/trials",
          "autotune/search_sec", "autotune/best_step_ms")


def find_results(path: str) -> List[str]:
    if os.path.isfile(path):
        return [path]
    return sorted(glob.glob(os.path.join(path, "autotune_result*.json")))


def _gb(v: Optional[float]) -> str:
    return f"{v / 1024**3:8.3f}" if v is not None else "     n/a"


def _ms(v: Optional[float]) -> str:
    return f"{v:9.2f}" if v is not None else "      n/a"


def render(doc: Dict[str, Any], source: str = "") -> str:
    lines = []
    adopted = doc.get("adopted", {})
    lines.append(
        f"autotune result{f' ({source})' if source else ''}: world "
        f"{doc.get('world_size')}, {len(doc.get('candidates', []))} "
        f"candidates, search {doc.get('search_sec', 0):.1f}s")
    limit = doc.get("hbm_limit_bytes")
    lines.append(
        f"  HBM limit: {_gb(limit).strip()} GB"
        + (f" (headroom_frac {doc.get('headroom_frac')})" if limit
           else " (unknown — capacity pruning inactive)"))
    lines.append(
        f"  adopted: '{adopted.get('name')}' at "
        f"{adopted.get('measured_step_ms')} ms/step "
        f"(default measured {doc.get('default_measured_step_ms')} ms), "
        f"config hash {adopted.get('config_hash')}")
    if adopted.get("overrides"):
        lines.append(f"  adopted overrides: "
                     f"{json.dumps(adopted['overrides'], sort_keys=True)}")
    header = (f"  {'candidate':<28} {'status':<16} {'proj GB':>8} "
              f"{'meas ms':>9}  reason")
    lines.append(header)
    lines.append("  " + "-" * (len(header) - 2))
    for r in doc.get("candidates", []):
        lines.append(
            f"  {r.get('name', '?'):<28} {r.get('status', '?'):<16} "
            f"{_gb(r.get('projected_device_bytes'))} "
            f"{_ms(r.get('measured_step_ms'))}  {r.get('reason') or ''}")
    for n in doc.get("notes", []):
        lines.append(f"  note: {n}")
    return "\n".join(lines)


def render_metrics(run_dir: str) -> str:
    """The autotune/* gauge values from any metrics*.jsonl beside the
    result (best-effort; absent file renders nothing)."""
    rows = {}
    for path in sorted(glob.glob(os.path.join(run_dir, "metrics*.jsonl"))):
        try:
            with open(path) as f:
                for line in f:
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue
                    name = rec.get("name", "")
                    if name in GAUGES:
                        rows[name] = rec.get("value")
        except OSError:
            continue
    if not rows:
        return ""
    return "\n".join([f"  {k}: {v}" for k, v in sorted(rows.items())])


def selftest() -> int:
    doc = {
        "format": 1, "world_size": 8, "search_sec": 3.2,
        "hbm_limit_bytes": 2 * 1024**3, "headroom_frac": 0.9,
        "default_measured_step_ms": 12.5,
        "adopted": {"name": "stage3-mb2x4", "measured_step_ms": 9.8,
                    "config_hash": "abc123", "overrides": {"zero_stage": 3}},
        "candidates": [
            {"name": "default", "status": "trialed",
             "projected_device_bytes": 1024**3, "measured_step_ms": 12.5,
             "reason": None},
            {"name": "stage3-mb2x4", "status": "adopted",
             "projected_device_bytes": 512 * 1024**2,
             "measured_step_ms": 9.8, "reason": None},
            {"name": "stage0-mb8x1", "status": "pruned_capacity",
             "projected_device_bytes": 4 * 1024**3,
             "measured_step_ms": None,
             "reason": "capacity: projects 4.00 GB per device > 90% of "
                       "the 2.00 GB HBM limit"},
        ],
        "notes": ["comm axes collapsed: single-slice mesh (dcn=1) has no "
                  "DCN hop to tune"],
    }
    text = render(doc, source="selftest")
    print(text)
    assert "adopted: 'stage3-mb2x4' at 9.8 ms/step" in text
    assert "pruned_capacity" in text and "4.00 GB" in text
    assert "default" in text and "12.50" in text
    assert "note: comm axes collapsed" in text
    print("selftest ok")
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if "--selftest" in argv:
        return selftest()
    if not argv:
        print(__doc__)
        return 2
    path = argv[0]
    results = find_results(path)
    if not results:
        print(f"no autotune_result*.json under {path!r}", file=sys.stderr)
        return 1
    for rp in results:
        with open(rp) as f:
            doc = json.load(f)
        print(render(doc, source=os.path.basename(rp)))
        if os.path.isdir(path):
            metrics = render_metrics(path)
            if metrics:
                print("  gauges:")
                print(metrics)
        print()
    return 0


if __name__ == "__main__":
    sys.exit(main())
