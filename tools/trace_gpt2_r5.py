"""Capture a jax.profiler trace of the bench-config GPT-2 train_batch on
the real chip (round-5: locate the residual gap between 60% MFU and the
HBM roofline before picking the next kernel lever)."""

import sys
import time

import jax
import numpy as np

sys.path.insert(0, "/root/repo")


def main():
    import deepspeed_tpu
    from deepspeed_tpu.models import make_gpt

    model, cfg = make_gpt("gpt2", dropout_rate=0.0, remat=False,
                          max_seq_len=512)
    rng = np.random.default_rng(0)
    micro_bs, seq, gas = 16, 512, 8
    batches = {"input_ids": rng.integers(0, cfg.vocab_size,
                                         (gas, micro_bs, seq),
                                         dtype=np.int32)}
    one = jax.tree_util.tree_map(lambda x: x[0], batches)
    params = model.init({"params": jax.random.PRNGKey(0),
                         "dropout": jax.random.PRNGKey(1)}, one)["params"]
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, params=params,
        config={
            "train_micro_batch_size_per_gpu": micro_bs,
            "gradient_accumulation_steps": gas,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
            "zero_optimization": {"stage": 2},
            "data_types": {"grad_accum_dtype": "bfloat16"},
            "bf16": {"enabled": True},
        })
    for _ in range(2):
        loss = engine.train_batch(batches)
    _ = float(loss)
    with jax.profiler.trace("/root/repo/profiles/gpt2_r5"):
        for _ in range(2):
            loss = engine.train_batch(batches)
        _ = float(loss)
    print("trace written", flush=True)


if __name__ == "__main__":
    main()
