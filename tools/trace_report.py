#!/usr/bin/env python
"""Summarize a Chrome trace-event file into a per-span time breakdown.

The artifact perf PRs cite: feed it the trace the engine's step tracer
writes (``telemetry.trace``; docs/OBSERVABILITY.md) and get a table of
where step time goes — total / count / mean / p50 / p99 / share per span
name — plus counter summaries (e.g. ``telemetry/recompiles``) and instant
events (retrace markers).

Parsing lives in the shared ``telemetry/traceparse.py`` (itself stdlib
only); this tool loads it by file path — no package import, no jax — so
it still runs anywhere a trace file lands. Rendering and the CLI stay
here.

Multiple traces (or a glob): every span row is prefixed with its source
host (``hostA:train_step``) — from each file's ``metadata.host``, or the
``trace.<host>.json`` filename component multi-host runs write — so one
table covers a fleet until ``tools/fleet_report.py`` replaces it.

Usage:
    python tools/trace_report.py TRACE.json [...] [--sort total|mean|count]
    python tools/trace_report.py 'run/telemetry/trace.*.json'
    python tools/trace_report.py --selftest
"""

import argparse
import importlib.util
import json
import os
import sys
import tempfile
from typing import Any, Dict


def _load_traceparse():
    """Load telemetry/traceparse.py by path: the module is stdlib-only,
    and a spec-load keeps this tool runnable on hosts where the package
    (and jax) cannot import."""
    cached = sys.modules.get("dstpu_traceparse")
    if cached is not None:
        return cached
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    path = os.path.join(root, "deepspeed_tpu", "telemetry", "traceparse.py")
    spec = importlib.util.spec_from_file_location("dstpu_traceparse", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    # One instance per process: a tool importing another tool (or tests
    # loading several) must see the same COLLECTIVE_RE/CATEGORIES objects.
    sys.modules["dstpu_traceparse"] = mod
    return mod


_tp = _load_traceparse()

# Historical module-level API (tests and other tools import these from
# here) — one implementation, in traceparse.
load_doc = _tp.load_doc
load_events = _tp.load_events
host_label = _tp.host_label
load_many = _tp.load_many
expand_paths = _tp.expand_paths
summarize = _tp.summarize
_percentile = _tp.percentile


def render(summary: Dict[str, Any], sort: str = "total") -> str:
    key = {"total": "total_ms", "mean": "mean_ms", "count": "count"}[sort]
    rows = sorted(summary["spans"], key=lambda r: r[key], reverse=True)
    out = []
    hdr = (f"{'span':<24} {'count':>7} {'total ms':>12} {'mean ms':>10} "
           f"{'p50 ms':>10} {'p99 ms':>10} {'share':>7}")
    out.append(hdr)
    out.append("-" * len(hdr))
    for r in rows:
        out.append(f"{r['name']:<24} {r['count']:>7} {r['total_ms']:>12.3f} "
                   f"{r['mean_ms']:>10.3f} {r['p50_ms']:>10.3f} "
                   f"{r['p99_ms']:>10.3f} {r['share']:>6.1%}")
    if not rows:
        out.append("(no complete spans in trace)")
    if summary["counters"]:
        out.append("")
        out.append("counters (latest value):")
        for name, v in sorted(summary["counters"].items()):
            out.append(f"  {name}: {v:g}")
    if summary["instants"]:
        out.append("")
        out.append("instant events:")
        for name, n in sorted(summary["instants"].items()):
            out.append(f"  {name}: x{n}")
    return "\n".join(out)


def _selftest() -> int:
    """Synthesize a trace, run the full load→summarize→render path, and
    verify the numbers — exercised from the test suite and CI."""
    events = []
    # 3 steps of a synthetic loop: dataloader 1ms, forward 4ms, backward
    # 0.01ms, optimizer_step 2ms; one ckpt pair; one recompile marker.
    t = 0.0
    for step in range(3):
        for name, dur_ms in (("dataloader", 1.0), ("forward", 4.0),
                             ("backward", 0.01), ("optimizer_step", 2.0)):
            events.append({"name": name, "ph": "X", "pid": 1, "tid": 1,
                           "ts": t, "dur": dur_ms * 1e3,
                           "args": {"step": step}})
            t += dur_ms * 1e3
    events.append({"name": "ckpt_snapshot", "ph": "X", "pid": 1, "tid": 2,
                   "ts": t, "dur": 500.0})
    events.append({"name": "ckpt_write", "ph": "X", "pid": 1, "tid": 2,
                   "ts": t + 500.0, "dur": 1500.0})
    # serving spans (serving/engine.py) ride the same timeline/report
    events.append({"name": "prefill", "ph": "X", "pid": 1, "tid": 1,
                   "ts": t + 2000.0, "dur": 800.0,
                   "args": {"rid": 0, "bucket": 16}})
    events.append({"name": "decode_step", "ph": "X", "pid": 1, "tid": 1,
                   "ts": t + 2800.0, "dur": 300.0, "args": {"active": 2}})
    events.append({"name": "recompile", "ph": "i", "s": "t", "pid": 1,
                   "tid": 1, "ts": t, "args": {"fn": "train_step"}})
    events.append({"name": "telemetry/recompiles", "ph": "C", "pid": 1,
                   "tid": 1, "ts": t, "args": {"value": 1.0}})
    with tempfile.TemporaryDirectory() as td:
        path = os.path.join(td, "trace.json")
        with open(path, "w") as f:
            json.dump({"traceEvents": events, "displayTimeUnit": "ms"}, f)
        summary = summarize(load_events(path))
        text = render(summary)
    by_name = {r["name"]: r for r in summary["spans"]}
    assert len(by_name) == 8, by_name.keys()
    assert by_name["forward"]["count"] == 3
    assert by_name["prefill"]["count"] == 1
    assert abs(by_name["decode_step"]["total_ms"] - 0.3) < 1e-9
    assert abs(by_name["forward"]["total_ms"] - 12.0) < 1e-9
    assert abs(by_name["optimizer_step"]["mean_ms"] - 2.0) < 1e-9
    assert summary["counters"]["telemetry/recompiles"] == 1.0
    assert summary["instants"]["recompile"] == 1
    assert "forward" in text and "share" in text
    top = max(summary["spans"], key=lambda r: r["total_ms"])
    assert top["name"] == "forward"
    # multi-file path: span rows gain their source-host prefix (metadata
    # host preferred, filename component as fallback)
    with tempfile.TemporaryDirectory() as td:
        for host, with_meta in (("hostA", True), ("hostB", False)):
            with open(os.path.join(td, f"trace.{host}.json"), "w") as f:
                doc = {"traceEvents": [
                    {"name": "train_step", "ph": "X", "pid": 1, "tid": 1,
                     "ts": 0.0, "dur": 1000.0}]}
                if with_meta:
                    doc["metadata"] = {"host": host}
                json.dump(doc, f)
        paths = expand_paths([os.path.join(td, "trace.*.json")])
        assert len(paths) == 2, paths
        multi = summarize(load_many(paths))
    names = {r["name"] for r in multi["spans"]}
    assert names == {"hostA:train_step", "hostB:train_step"}, names
    print(text)
    print("\nselftest ok")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("trace", nargs="*",
                    help="Chrome trace-event JSON file(s) or glob; with "
                         "more than one, rows are host-prefixed")
    ap.add_argument("--sort", choices=("total", "mean", "count"),
                    default="total")
    ap.add_argument("--json", action="store_true",
                    help="emit the summary as JSON instead of a table")
    ap.add_argument("--selftest", action="store_true",
                    help="run the built-in round-trip check and exit")
    args = ap.parse_args(argv)
    if args.selftest:
        return _selftest()
    if not args.trace:
        ap.error("trace file required (or --selftest)")
    paths = expand_paths(args.trace)
    events = (load_events(paths[0]) if len(paths) == 1
              else load_many(paths))
    summary = summarize(events)
    if args.json:
        print(json.dumps(summary, indent=1))
    else:
        print(render(summary, sort=args.sort))
    return 0


if __name__ == "__main__":
    sys.exit(main())
