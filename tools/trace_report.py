#!/usr/bin/env python
"""Summarize a Chrome trace-event file into a per-span time breakdown.

The artifact perf PRs cite: feed it the trace the engine's step tracer
writes (``telemetry.trace``; docs/OBSERVABILITY.md) and get a table of
where step time goes — total / count / mean / p50 / p99 / share per span
name — plus counter summaries (e.g. ``telemetry/recompiles``) and instant
events (retrace markers).

Standalone on purpose: imports nothing beyond the stdlib, so it runs
anywhere a trace file lands (including hosts without jax installed).

Multiple traces (or a glob): every span row is prefixed with its source
host (``hostA:train_step``) — from each file's ``metadata.host``, or the
``trace.<host>.json`` filename component multi-host runs write — so one
table covers a fleet until ``tools/fleet_report.py`` replaces it.

Usage:
    python tools/trace_report.py TRACE.json [...] [--sort total|mean|count]
    python tools/trace_report.py 'run/telemetry/trace.*.json'
    python tools/trace_report.py --selftest
"""

import argparse
import glob as _glob
import json
import os
import sys
import tempfile
from typing import Any, Dict, List, Optional


def load_doc(path: str) -> Dict[str, Any]:
    with open(path) as f:
        doc = json.load(f)
    if isinstance(doc, list):  # bare-array Chrome trace variant
        doc = {"traceEvents": doc}
    if not isinstance(doc, dict):
        raise ValueError(f"{path}: not a Chrome trace (dict or list)")
    events = doc.get("traceEvents", [])
    if not isinstance(events, list):
        raise ValueError(f"{path}: traceEvents is not a list")
    return doc


def load_events(path: str) -> List[Dict[str, Any]]:
    return load_doc(path)["traceEvents"]


def host_label(path: str, doc: Dict[str, Any]) -> str:
    """Source-host label: trace metadata first, then the
    ``<stem>.<host>.json`` filename component, then the file stem."""
    host = (doc.get("metadata") or {}).get("host")
    if host:
        return str(host)
    stem = os.path.basename(path)
    if stem.endswith(".json"):
        stem = stem[:-len(".json")]
    parts = stem.split(".")
    return parts[-1] if len(parts) > 1 else stem


def load_many(paths: List[str]) -> List[Dict[str, Any]]:
    """Load several trace files into one event list, each event's name
    prefixed with its source host."""
    events: List[Dict[str, Any]] = []
    for path in paths:
        doc = load_doc(path)
        label = host_label(path, doc)
        for ev in doc["traceEvents"]:
            if "name" in ev and ev.get("ph") != "M":
                ev = dict(ev)
                ev["name"] = f"{label}:{ev['name']}"
            events.append(ev)
    return events


def expand_paths(args_traces: List[str]) -> List[str]:
    """Expand glob patterns (quoted globs reach us unexpanded) and keep
    explicit paths as-is."""
    out: List[str] = []
    for t in args_traces:
        matches = sorted(_glob.glob(t))
        out.extend(matches if matches else [t])
    return out


def _percentile(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    if len(sorted_vals) == 1:
        return sorted_vals[0]
    pos = (q / 100.0) * (len(sorted_vals) - 1)
    lo = int(pos)
    hi = min(lo + 1, len(sorted_vals) - 1)
    return sorted_vals[lo] + (sorted_vals[hi] - sorted_vals[lo]) * (pos - lo)


def summarize(events: List[Dict[str, Any]]) -> Dict[str, Any]:
    spans: Dict[str, List[float]] = {}
    counters: Dict[str, float] = {}
    instants: Dict[str, int] = {}
    for ev in events:
        ph = ev.get("ph")
        name = ev.get("name", "<unnamed>")
        if ph == "X":
            spans.setdefault(name, []).append(float(ev.get("dur", 0.0)))
        elif ph == "C":
            args = ev.get("args") or {}
            # last write wins: counters carry running totals
            for k, v in args.items():
                counters[name if k == "value" else f"{name}.{k}"] = float(v)
        elif ph == "i" or ph == "I":
            instants[name] = instants.get(name, 0) + 1
    rows = []
    for name, durs in spans.items():
        durs.sort()
        total = sum(durs)
        rows.append({
            "name": name,
            "count": len(durs),
            "total_ms": total / 1e3,
            "mean_ms": total / len(durs) / 1e3,
            "p50_ms": _percentile(durs, 50) / 1e3,
            "p99_ms": _percentile(durs, 99) / 1e3,
        })
    grand = sum(r["total_ms"] for r in rows) or 1.0
    for r in rows:
        r["share"] = r["total_ms"] / grand
    return {"spans": rows, "counters": counters, "instants": instants}


def render(summary: Dict[str, Any], sort: str = "total") -> str:
    key = {"total": "total_ms", "mean": "mean_ms", "count": "count"}[sort]
    rows = sorted(summary["spans"], key=lambda r: r[key], reverse=True)
    out = []
    hdr = (f"{'span':<24} {'count':>7} {'total ms':>12} {'mean ms':>10} "
           f"{'p50 ms':>10} {'p99 ms':>10} {'share':>7}")
    out.append(hdr)
    out.append("-" * len(hdr))
    for r in rows:
        out.append(f"{r['name']:<24} {r['count']:>7} {r['total_ms']:>12.3f} "
                   f"{r['mean_ms']:>10.3f} {r['p50_ms']:>10.3f} "
                   f"{r['p99_ms']:>10.3f} {r['share']:>6.1%}")
    if not rows:
        out.append("(no complete spans in trace)")
    if summary["counters"]:
        out.append("")
        out.append("counters (latest value):")
        for name, v in sorted(summary["counters"].items()):
            out.append(f"  {name}: {v:g}")
    if summary["instants"]:
        out.append("")
        out.append("instant events:")
        for name, n in sorted(summary["instants"].items()):
            out.append(f"  {name}: x{n}")
    return "\n".join(out)


def _selftest() -> int:
    """Synthesize a trace, run the full load→summarize→render path, and
    verify the numbers — exercised from the test suite and CI."""
    events = []
    # 3 steps of a synthetic loop: dataloader 1ms, forward 4ms, backward
    # 0.01ms, optimizer_step 2ms; one ckpt pair; one recompile marker.
    t = 0.0
    for step in range(3):
        for name, dur_ms in (("dataloader", 1.0), ("forward", 4.0),
                             ("backward", 0.01), ("optimizer_step", 2.0)):
            events.append({"name": name, "ph": "X", "pid": 1, "tid": 1,
                           "ts": t, "dur": dur_ms * 1e3,
                           "args": {"step": step}})
            t += dur_ms * 1e3
    events.append({"name": "ckpt_snapshot", "ph": "X", "pid": 1, "tid": 2,
                   "ts": t, "dur": 500.0})
    events.append({"name": "ckpt_write", "ph": "X", "pid": 1, "tid": 2,
                   "ts": t + 500.0, "dur": 1500.0})
    # serving spans (serving/engine.py) ride the same timeline/report
    events.append({"name": "prefill", "ph": "X", "pid": 1, "tid": 1,
                   "ts": t + 2000.0, "dur": 800.0,
                   "args": {"rid": 0, "bucket": 16}})
    events.append({"name": "decode_step", "ph": "X", "pid": 1, "tid": 1,
                   "ts": t + 2800.0, "dur": 300.0, "args": {"active": 2}})
    events.append({"name": "recompile", "ph": "i", "s": "t", "pid": 1,
                   "tid": 1, "ts": t, "args": {"fn": "train_step"}})
    events.append({"name": "telemetry/recompiles", "ph": "C", "pid": 1,
                   "tid": 1, "ts": t, "args": {"value": 1.0}})
    with tempfile.TemporaryDirectory() as td:
        path = os.path.join(td, "trace.json")
        with open(path, "w") as f:
            json.dump({"traceEvents": events, "displayTimeUnit": "ms"}, f)
        summary = summarize(load_events(path))
        text = render(summary)
    by_name = {r["name"]: r for r in summary["spans"]}
    assert len(by_name) == 8, by_name.keys()
    assert by_name["forward"]["count"] == 3
    assert by_name["prefill"]["count"] == 1
    assert abs(by_name["decode_step"]["total_ms"] - 0.3) < 1e-9
    assert abs(by_name["forward"]["total_ms"] - 12.0) < 1e-9
    assert abs(by_name["optimizer_step"]["mean_ms"] - 2.0) < 1e-9
    assert summary["counters"]["telemetry/recompiles"] == 1.0
    assert summary["instants"]["recompile"] == 1
    assert "forward" in text and "share" in text
    top = max(summary["spans"], key=lambda r: r["total_ms"])
    assert top["name"] == "forward"
    # multi-file path: span rows gain their source-host prefix (metadata
    # host preferred, filename component as fallback)
    with tempfile.TemporaryDirectory() as td:
        for host, with_meta in (("hostA", True), ("hostB", False)):
            with open(os.path.join(td, f"trace.{host}.json"), "w") as f:
                doc = {"traceEvents": [
                    {"name": "train_step", "ph": "X", "pid": 1, "tid": 1,
                     "ts": 0.0, "dur": 1000.0}]}
                if with_meta:
                    doc["metadata"] = {"host": host}
                json.dump(doc, f)
        paths = expand_paths([os.path.join(td, "trace.*.json")])
        assert len(paths) == 2, paths
        multi = summarize(load_many(paths))
    names = {r["name"] for r in multi["spans"]}
    assert names == {"hostA:train_step", "hostB:train_step"}, names
    print(text)
    print("\nselftest ok")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("trace", nargs="*",
                    help="Chrome trace-event JSON file(s) or glob; with "
                         "more than one, rows are host-prefixed")
    ap.add_argument("--sort", choices=("total", "mean", "count"),
                    default="total")
    ap.add_argument("--json", action="store_true",
                    help="emit the summary as JSON instead of a table")
    ap.add_argument("--selftest", action="store_true",
                    help="run the built-in round-trip check and exit")
    args = ap.parse_args(argv)
    if args.selftest:
        return _selftest()
    if not args.trace:
        ap.error("trace file required (or --selftest)")
    paths = expand_paths(args.trace)
    events = (load_events(paths[0]) if len(paths) == 1
              else load_many(paths))
    summary = summarize(events)
    if args.json:
        print(json.dumps(summary, indent=1))
    else:
        print(render(summary, sort=args.sort))
    return 0


if __name__ == "__main__":
    sys.exit(main())
