// Native async-I/O primitives for the NVMe swap tier.
//
// The reference's aio extension (csrc/aio/py_lib/deepspeed_py_aio.cpp) wraps
// libaio submission/completion queues so tensor reads/writes bypass the
// Python interpreter and page cache (O_DIRECT). This module is the
// deepspeed_tpu equivalent built on plain POSIX pread/pwrite:
//   - GIL released for the entire transfer (true overlap with host compute
//     and other I/O threads; Python-side ThreadPoolExecutor provides the
//     queue, mirroring aio_handle's thread pool),
//   - optional O_DIRECT, taken only when the caller's buffer pointer and
//     length are both 4 KiB-aligned (the Python swapper stages transfers
//     through aligned, block-padded buffers so the flag engages; unaligned
//     callers transparently fall back to buffered I/O),
//   - single syscall-loop per tensor (no Python per-chunk overhead).
//
// Exposed: write_buffer(path, buffer, use_direct) -> bytes written
//          read_buffer(path, buffer, use_direct)  -> bytes read
// Buffers are any objects exporting the (writable, for reads) buffer
// protocol — numpy arrays pass zero-copy.

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <cerrno>
#include <cstdlib>
#include <cstring>
#include <fcntl.h>
#include <sys/stat.h>
#include <sys/types.h>
#include <unistd.h>

namespace {

constexpr size_t kAlign = 4096;

// pwrite the whole span; returns bytes written or -1.
ssize_t write_all(int fd, const char* data, size_t n) {
    size_t done = 0;
    while (done < n) {
        ssize_t w = pwrite(fd, data + done, n - done, (off_t)done);
        if (w < 0) {
            if (errno == EINTR) continue;
            return -1;
        }
        done += (size_t)w;
    }
    return (ssize_t)done;
}

ssize_t read_all(int fd, char* data, size_t n) {
    size_t done = 0;
    while (done < n) {
        ssize_t r = pread(fd, data + done, n - done, (off_t)done);
        if (r < 0) {
            if (errno == EINTR) continue;
            return -1;
        }
        if (r == 0) break;  // EOF
        done += (size_t)r;
    }
    return (ssize_t)done;
}

PyObject* write_buffer(PyObject*, PyObject* args) {
    const char* path;
    Py_buffer buf;
    int use_direct = 0;
    if (!PyArg_ParseTuple(args, "sy*|p", &path, &buf, &use_direct)) {
        return nullptr;
    }
    ssize_t result = -1;
    int saved_errno = 0;
    Py_BEGIN_ALLOW_THREADS
    int flags = O_WRONLY | O_CREAT | O_TRUNC;
#ifdef O_DIRECT
    // O_DIRECT needs aligned offset/length/buffer; fall back transparently
    // when the buffer is unaligned (numpy arrays usually are 64-aligned,
    // not 4096) — correctness first, the flag is a fast path.
    if (use_direct && ((uintptr_t)buf.buf % kAlign == 0) &&
        ((size_t)buf.len % kAlign == 0)) {
        flags |= O_DIRECT;
    }
#endif
    int fd = open(path, flags, 0644);
#ifdef O_DIRECT
    if (fd < 0 && (flags & O_DIRECT)) {
        // Filesystem rejects O_DIRECT (tmpfs, some NFS/overlay mounts):
        // buffered I/O is the correctness path, the flag is a fast path.
        flags &= ~O_DIRECT;
        fd = open(path, flags, 0644);
    }
#endif
    if (fd >= 0) {
        result = write_all(fd, (const char*)buf.buf, (size_t)buf.len);
        saved_errno = errno;
        close(fd);
    } else {
        saved_errno = errno;
    }
    Py_END_ALLOW_THREADS
    PyBuffer_Release(&buf);
    if (result < 0) {
        errno = saved_errno;
        PyErr_SetFromErrnoWithFilename(PyExc_OSError, path);
        return nullptr;
    }
    return PyLong_FromSsize_t(result);
}

PyObject* read_buffer(PyObject*, PyObject* args) {
    const char* path;
    Py_buffer buf;
    int use_direct = 0;
    if (!PyArg_ParseTuple(args, "sw*|p", &path, &buf, &use_direct)) {
        return nullptr;
    }
    ssize_t result = -1;
    int saved_errno = 0;
    Py_BEGIN_ALLOW_THREADS
    int flags = O_RDONLY;
#ifdef O_DIRECT
    if (use_direct && ((uintptr_t)buf.buf % kAlign == 0) &&
        ((size_t)buf.len % kAlign == 0)) {
        flags |= O_DIRECT;
    }
#endif
    int fd = open(path, flags);
#ifdef O_DIRECT
    if (fd < 0 && (flags & O_DIRECT)) {
        flags &= ~O_DIRECT;
        fd = open(path, flags);
    }
#endif
    if (fd >= 0) {
        result = read_all(fd, (char*)buf.buf, (size_t)buf.len);
        saved_errno = errno;
        close(fd);
    } else {
        saved_errno = errno;
    }
    Py_END_ALLOW_THREADS
    PyBuffer_Release(&buf);
    if (result < 0) {
        errno = saved_errno;
        PyErr_SetFromErrnoWithFilename(PyExc_OSError, path);
        return nullptr;
    }
    return PyLong_FromSsize_t(result);
}

PyMethodDef methods[] = {
    {"write_buffer", write_buffer, METH_VARARGS,
     "write_buffer(path, buffer, use_direct=False) -> bytes written"},
    {"read_buffer", read_buffer, METH_VARARGS,
     "read_buffer(path, writable_buffer, use_direct=False) -> bytes read"},
    {nullptr, nullptr, 0, nullptr}};

PyModuleDef module = {PyModuleDef_HEAD_INIT, "_dstpu_aio",
                      "Native buffered/direct tensor file I/O (GIL-free)",
                      -1, methods};

}  // namespace

PyMODINIT_FUNC PyInit__dstpu_aio() { return PyModule_Create(&module); }
