"""Headline benchmark — BERT-large ZeRO-2 pretraining throughput per chip.

Mirrors the reference's flagship number: BERT-Large seq-128 pretraining at
272 samples/s on one V100 with the fused CUDA transformer kernel
(reference docs/_tutorials/bert-pretraining.md:387, BASELINE.md). Here the
same workload runs through the TPU engine (bf16, ZeRO-2 placement, fused
train_batch step) on however many chips are visible; the reported metric is
samples/sec/chip and ``vs_baseline`` is the ratio against the 272 V100
number.

Prints exactly one JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}
"""

import json
import sys
import time

import jax
import numpy as np

BASELINE_SAMPLES_PER_SEC = 272.0  # 1x V100, BERT-Large seq128, fused kernels


def main():
    platform = jax.devices()[0].platform
    on_tpu = platform == "tpu"

    import deepspeed_tpu
    from deepspeed_tpu.models import make_bert

    if on_tpu:
        model_name, micro_bs, seq, steps, warmup = "bert-large", 32, 128, 10, 3
    else:  # smoke mode off-TPU (CI/dev boxes) — same code path, tiny shapes
        model_name, micro_bs, seq, steps, warmup = "tiny", 8, 64, 3, 1

    model, cfg = make_bert(model_name, dropout_rate=0.0, remat=on_tpu,
                           max_seq_len=max(seq, 128))
    rng = np.random.default_rng(0)
    n_chips = max(len(jax.devices()), 1)
    global_bs = micro_bs * n_chips

    def make_batch():
        ids = rng.integers(0, cfg.vocab_size, (global_bs, seq), dtype=np.int32)
        labels = np.where(rng.random((global_bs, seq)) < 0.15, ids, -100)
        return {"input_ids": ids,
                "attention_mask": np.ones((global_bs, seq), np.int32),
                "labels": labels.astype(np.int32)}

    ds_config = {
        "train_micro_batch_size_per_gpu": micro_bs,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "Lamb", "params": {"lr": 2e-3}},
        "zero_optimization": {"stage": 2},
        "bf16": {"enabled": True},
    }
    params = model.init({"params": jax.random.PRNGKey(0),
                         "dropout": jax.random.PRNGKey(1)}, make_batch())["params"]
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, params=params,
                                               config=ds_config)

    batch = make_batch()
    for _ in range(warmup):
        loss = engine(batch)
        engine.backward(loss)
        engine.step()
    jax.block_until_ready(engine.state.params)

    t0 = time.perf_counter()
    for _ in range(steps):
        loss = engine(batch)
        engine.backward(loss)
        engine.step()
    jax.block_until_ready(engine.state.params)
    dt = time.perf_counter() - t0

    samples_per_sec = global_bs * steps / dt
    per_chip = samples_per_sec / n_chips
    result = {
        "metric": f"BERT-{'large' if on_tpu else 'tiny'} seq{seq} ZeRO-2 "
                  f"pretrain throughput ({platform})",
        "value": round(per_chip, 2),
        "unit": "samples/sec/chip",
        "vs_baseline": round(per_chip / BASELINE_SAMPLES_PER_SEC, 4),
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
