"""Headline benchmark — BERT-large ZeRO-2 pretraining throughput per chip.

Mirrors the reference's flagship numbers (BASELINE.md):
- BERT-Large seq-128 pretraining: 272 samples/s on 1x V100 with the fused
  CUDA transformer kernel (docs/_tutorials/bert-pretraining.md:387).
- BERT-Large seq-512: 52 samples/s (same table).
- GPT-2 tokens/sec/chip (BASELINE.json second tracked metric).

The headline metric rides in the single stdout JSON line; the secondary
GPT-2 number, the seq-512 BERT row, achieved TFLOP/s and MFU are extra keys
on the same line (stdout stays exactly one JSON line). Diagnostics print to
stderr.

Methodology: the fused ``engine.train_batch`` path — one XLA dispatch per
optimizer step (micro-batch scan + apply in a single program), steps queued
asynchronously, one scalar loss fetch closing the timed window. Through the
axon TPU tunnel a per-step host sync costs ~100 ms of pure RTT, which is
dispatch-model noise, not device throughput; the reference's numbers are
likewise device-side. Gradient accumulation (gas=8) amortises the optimizer
apply exactly as the reference's BERT configs do (large effective batches).
"""

import json
import os
import sys
import time
import traceback

import jax
import numpy as np

# ONE source of truth for MFU math + per-chip peak TFLOP/s tables
# (telemetry/goodput.py's engine/mfu gauge divides by the same numbers).
from deepspeed_tpu.profiling.flops_profiler import mfu as compute_mfu
from deepspeed_tpu.profiling.flops_profiler import peak_tflops

# Partial results land here after EVERY completed section so a transient
# tunnel failure (the round-4 driver run died on a dropped remote_compile
# connection ~2 min in) can never zero the whole record: whatever rows
# finished are already on disk, and main() exits 0 with those rows on
# stdout regardless of later sections failing.
PARTIAL_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "BENCH_partial.json")

BASELINE_BERT_SEQ128 = 272.0   # samples/s, 1x V100, fused kernels
BASELINE_BERT_SEQ512 = 52.0    # samples/s, 1x V100
# GPT-2 has no single published reference tokens/s in-tree; BASELINE.json
# tracks it as a metric. Use the V100 BERT-large FLOP rate (64 TFLOP/s)
# converted to GPT-2-small tokens as the comparable bar: 64e12 / (6*124e6)
# ~= 86k tokens/s.
BASELINE_GPT2_TOKENS = 86000.0


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def train_flops_per_step(n_params, batch, seq, hidden, layers):
    """Analytic fwd+bwd FLOPs: 6*N per token for the dense path plus the
    attention score/value matmuls (12*S*H per token per layer, fwd+bwd)."""
    tokens = batch * seq
    dense = 6.0 * n_params * tokens
    attn = 12.0 * layers * hidden * seq * tokens
    return dense + attn


def count_params(tree):
    return sum(int(np.prod(x.shape)) for x in jax.tree_util.tree_leaves(tree))


# Set when time_train_batches lost windows to a transient mid-run
# failure; _section_rows stamps the NEXT recorded row "partial": 1 and
# run_section keeps rc=1 semantics for that section (evidence recorded,
# round not green — the r04 remote-compile read-error hardening).
_TIMING_PARTIAL = {"flag": False}


def time_train_batches(engine, batches, steps, warmup, windows=3):
    """Queue `steps` fused steps asynchronously; a scalar loss fetch closes
    each window (block_until_ready does not reliably fence the tunnel).

    Best-of-`windows`: the shared axon tunnel shows ±10% run-to-run drift
    from external load (measured in round 3, tools/ VAR_probe), so a single
    window under-reports device throughput; the fastest of three
    consecutive windows approximates the uncontended rate, which is what
    the reference's published per-GPU numbers report too.

    Median-of-windows is reported alongside (ADVICE r3): the `vs_baseline`
    ratios divide a best-case window by average-style reference constants,
    so the median gives the drift-inclusive view of the same run.

    A TRANSIENT failure mid-window (the round-4 killer: a dropped
    remote_compile connection surfacing as a read error inside
    train_batch) no longer zeroes the whole section: completed windows
    are kept, the row is stamped partial, and only a failure before the
    FIRST window completes still propagates to run_section's
    retry/error path."""
    for _ in range(warmup):
        loss = engine.train_batch(batches)
    _ = float(loss)
    times = []
    for _ in range(max(1, windows)):
        t0 = time.perf_counter()
        try:
            for _ in range(steps):
                loss = engine.train_batch(batches)
            _ = float(loss)
        except Exception as e:  # noqa: BLE001 — screened by _is_transient
            if times and _is_transient(e):
                log(f"[bench] transient failure after {len(times)} "
                    f"window(s) — recording a partial row: "
                    f"{type(e).__name__}: {e}")
                _TIMING_PARTIAL["flag"] = True
                break
            raise
        times.append(time.perf_counter() - t0)
    return min(times), float(np.median(times))


def bench_bert(seq, micro_bs, gas, steps, warmup, on_tpu):
    import deepspeed_tpu
    from deepspeed_tpu.models import make_bert

    name = "bert-large" if on_tpu else "tiny"
    # No remat: at these batch sizes HBM has headroom and full recompute
    # would pay ~30% extra FLOPs for nothing.
    model, cfg = make_bert(name, dropout_rate=0.0, remat=False,
                           max_seq_len=max(seq, 128))
    rng = np.random.default_rng(0)
    n_chips = max(len(jax.devices()), 1)
    bs = micro_bs * n_chips
    ids = rng.integers(0, cfg.vocab_size, (gas, bs, seq), dtype=np.int32)
    labels = np.where(rng.random((gas, bs, seq)) < 0.15, ids, -100)
    batches = {"input_ids": ids,
               "attention_mask": np.ones((gas, bs, seq), np.int32),
               "labels": labels.astype(np.int32)}
    one = jax.tree_util.tree_map(lambda x: x[0], batches)
    params = model.init({"params": jax.random.PRNGKey(0),
                         "dropout": jax.random.PRNGKey(1)}, one)["params"]
    n_params = count_params(params)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, params=params,
        config={
            "train_micro_batch_size_per_gpu": micro_bs,
            "gradient_accumulation_steps": gas,
            "optimizer": {"type": "Lamb", "params": {"lr": 2e-3}},
            "zero_optimization": {"stage": 2},
            # bf16 accumulator ≡ the reference's fp16 grad buffers; gas=8
            # amortizes the (LAMB-norm-heavy) apply — measured +18% on
            # BERT-128 (AB_final_cfg, r3).
            "data_types": {"grad_accum_dtype": "bfloat16"},
            "bf16": {"enabled": True},
        })
    dt, dt_med = time_train_batches(engine, batches, steps, warmup)
    samples = gas * bs * steps
    sps = samples / dt / n_chips
    flops = train_flops_per_step(n_params, samples, seq,
                                 cfg.hidden_size, cfg.num_layers)
    tflops = flops / dt / 1e12 / n_chips
    return sps, tflops, n_params, samples / dt_med / n_chips, flops, dt


def bench_gpt2(steps, warmup, on_tpu, dropout_rate=0.0):
    import deepspeed_tpu
    from deepspeed_tpu.models import make_gpt

    name, micro_bs, seq, gas = (("gpt2", 16, 512, 8) if on_tpu
                                else ("tiny", 4, 64, 2))
    model, cfg = make_gpt(name, dropout_rate=dropout_rate, remat=False,
                          max_seq_len=max(seq, 128))
    rng = np.random.default_rng(0)
    n_chips = max(len(jax.devices()), 1)
    bs = micro_bs * n_chips
    batches = {"input_ids": rng.integers(0, cfg.vocab_size, (gas, bs, seq),
                                         dtype=np.int32)}
    one = jax.tree_util.tree_map(lambda x: x[0], batches)
    params = model.init({"params": jax.random.PRNGKey(0),
                         "dropout": jax.random.PRNGKey(1)}, one)["params"]
    n_params = count_params(params)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, params=params,
        config={
            "train_micro_batch_size_per_gpu": micro_bs,
            "gradient_accumulation_steps": gas,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
            "zero_optimization": {"stage": 2},
            "data_types": {"grad_accum_dtype": "bfloat16"},
            "bf16": {"enabled": True},
        })
    dt, dt_med = time_train_batches(engine, batches, steps, warmup)
    tokens = gas * bs * seq * steps
    tokens_per_sec = tokens / dt / n_chips
    flops = train_flops_per_step(n_params, gas * bs * steps, seq,
                                 cfg.hidden_size, cfg.num_layers)
    tflops = flops / dt / 1e12 / n_chips
    return tokens_per_sec, tflops, tokens / dt_med / n_chips, flops, dt


def bench_gpt2_long(steps, warmup, sparse: bool, seq=16384):
    """Long-sequence row (seq 16384): dense flash attention vs config-driven
    BigBird block-sparse — the reference's 10x-longer-sequence story
    (BASELINE.md sparse attention row), driven through the
    `sparse_attention` config block end-to-end. Measured r4 (fwd+bwd
    stacks): bigbird blk-256 at 5.8% density = 3.0x dense flash at 16k,
    1.5x (blk-512) at 4k (tools/probe_sparse_block.py)."""
    import deepspeed_tpu
    from deepspeed_tpu.models import make_gpt

    micro_bs, gas = 1, 4
    model, cfg = make_gpt("gpt2", dropout_rate=0.0, remat=False,
                          max_seq_len=seq)
    rng = np.random.default_rng(0)
    batches = {"input_ids": rng.integers(0, cfg.vocab_size,
                                         (gas, micro_bs, seq),
                                         dtype=np.int32)}
    one = jax.tree_util.tree_map(lambda x: x[0], batches)
    params = model.init({"params": jax.random.PRNGKey(0),
                         "dropout": jax.random.PRNGKey(1)}, one)["params"]
    config = {
        "train_micro_batch_size_per_gpu": micro_bs,
        "gradient_accumulation_steps": gas,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
        "zero_optimization": {"stage": 2},
        "data_types": {"grad_accum_dtype": "bfloat16"},
        "bf16": {"enabled": True},
    }
    if sparse:
        config["sparse_attention"] = {
            "mode": "bigbird", "block": 256, "num_random_blocks": 1,
            "num_sliding_window_blocks": 3, "num_global_blocks": 1,
            "attention": "unidirectional",
        }
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, params=params, config=config)
    dt, _ = time_train_batches(engine, batches, steps, warmup, windows=2)
    tokens = gas * micro_bs * seq * steps
    return tokens / dt


def bench_inference(batch, new_tokens=128, prompt=128, windows=3):
    """Generation throughput (tokens/s) through the inference engine's
    jitted prefill+decode: the reference stakes latency claims on its
    inference kernels (docs/_tutorials/inference-tutorial.md); this is the
    capability-parity evidence row (KV cache, one dispatch per call)."""
    import deepspeed_tpu
    from deepspeed_tpu.models import make_gpt

    model, cfg = make_gpt("gpt2", dropout_rate=0.0,
                          max_seq_len=prompt + new_tokens)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, cfg.vocab_size, (batch, prompt), dtype=np.int32)
    params = model.init({"params": jax.random.PRNGKey(0),
                         "dropout": jax.random.PRNGKey(1)},
                        {"input_ids": ids[:1]})["params"]
    eng = deepspeed_tpu.init_inference(model, params=params)
    out = eng.generate(ids, max_new_tokens=new_tokens)   # compile
    _ = np.asarray(out[0, -1])
    best = float("inf")
    for _ in range(windows):
        t0 = time.perf_counter()
        out = eng.generate(ids, max_new_tokens=new_tokens)
        _ = np.asarray(out[0, -1])   # fence
        best = min(best, time.perf_counter() - t0)
    return batch * new_tokens / best


# Serving-section config (bench rows must stay attributable: this block
# is recorded verbatim in the environment block). Tiny GPT family on
# purpose — the section is CPU-runnable and measures the serving
# machinery (continuous batching, paged KV, bucketed prefill), not model
# FLOPs.
SERVING_BENCH_CFG = {
    "max_batch_size": 4,
    "kv_block_size": 16,
    "kv_num_blocks": 128,
    "int8_kv_cache": False,
    "max_model_len": 112,
}

# Request-observatory config for the serving section
# (telemetry/requests.py): sources the TPOT/e2e percentile rows from the
# real per-request accounting surface. Recorded in the environment block
# like SERVING_BENCH_CFG so the latency rows stay attributable.
SERVING_REQUESTS_CFG = {
    "enabled": True,
    "window_sec": 10.0,
}

# Resilience config for the serving overload A/B row
# (serving/resilience.py; docs/SERVING.md "Serving under failure").
# Depth-bounded shedding only: deterministic on a cold engine, so the
# A/B row is reproducible. Recorded in the environment block.
SERVING_RESILIENCE_CFG = {
    "enabled": True,
    "max_queue_depth": 6,
}


def bench_serving(n_requests=12):
    """Offline serving throughput + latency SLOs through the
    continuous-batching engine (serving/engine.py, docs/SERVING.md): a
    fixed mixed trace of prompt/output lengths submitted up front,
    measured to drain. TTFT comes from the engine's histogram; TPOT/e2e
    come from the request observatory (telemetry/requests.py) enabled
    per SERVING_REQUESTS_CFG. Returns (tokens/s, ttft p50 ms,
    ttft p99 ms, mean occupancy, tpot p50 ms, tpot p99 ms, e2e p99
    ms)."""
    import tempfile

    import deepspeed_tpu
    from deepspeed_tpu.models import make_gpt

    model, cfg = make_gpt("tiny", dropout_rate=0.0, max_seq_len=128)
    rng = np.random.default_rng(0)
    params = model.init({"params": jax.random.PRNGKey(0),
                         "dropout": jax.random.PRNGKey(1)},
                        {"input_ids": np.zeros((1, 8), np.int32)})["params"]
    # memory-sink metrics: the latency percentiles come from the real
    # telemetry surface; the request records land in a throwaway dir.
    with tempfile.TemporaryDirectory() as td:
        srv = deepspeed_tpu.init_serving(
            model, params=params,
            config={"serving": SERVING_BENCH_CFG,
                    "telemetry": {"enabled": True, "dir": td,
                                  "metrics": {"sinks": ["memory"]},
                                  "trace": {"enabled": False},
                                  "requests": dict(SERVING_REQUESTS_CFG)}})
        prompts = [rng.integers(0, cfg.vocab_size,
                                (int(rng.integers(6, 48)),)).tolist()
                   for _ in range(n_requests)]
        outs = [int(rng.integers(8, 48)) for _ in range(n_requests)]
        # warmup: compile the decode program AND every prefill bucket the
        # trace will hit off the clock (one representative prompt per
        # bucket), so the timed window measures the serving machinery,
        # not XLA compile latency
        seen = set()
        for p in prompts:
            b = srv._bucket_of(len(p))
            if b not in seen:
                seen.add(b)
                srv.submit(p, 2)
        srv.run_until_complete()
        srv.results.clear()
        # drop warmup observations: the compile-latency TTFTs/TPOTs and
        # warmup decode steps must not leak into the reported
        # percentiles/occupancy
        reg = srv.telemetry.registry
        for tag in ("serving/ttft_ms", "requests/tpot_ms",
                    "requests/e2e_ms", "requests/queue_wait_ms"):
            reg.histogram(tag).reset()
        srv.stats.update(decode_steps=0, occupancy_sum=0.0,
                         slot_assignments={})
        t0 = time.perf_counter()
        for p, n in zip(prompts, outs):
            srv.submit(p, n)
        srv.run_until_complete()
        dt = time.perf_counter() - t0
        hist = reg.histogram("serving/ttft_ms")
        tpot = reg.histogram("requests/tpot_ms")
        e2e = reg.histogram("requests/e2e_ms")
        out = (sum(outs) / dt, hist.percentile(50), hist.percentile(99),
               srv.mean_occupancy, tpot.percentile(50),
               tpot.percentile(99), e2e.percentile(99))
        srv.close()
    return out


def bench_serving_fastpath():
    """Decode fast-path A/B rows (docs/SERVING.md "Decode fast path"),
    CPU-runnable like bench_serving: (1) mean decode-step wall ms on the
    same mixed trace with the gather program vs the paged decode-attention
    kernel (Pallas interpreter off-TPU — the row exists so a TPU round
    can show the streaming win; outputs are asserted token-identical);
    (2) cold vs warm-prompt-head TTFT under the prefix cache; (3)
    speculative-decode accept rate and effective tokens per verify step.
    Returns a dict of row values."""
    import jax.numpy as jnp

    import deepspeed_tpu
    from deepspeed_tpu.models import make_gpt

    # fp32 like tests/test_serving.py: the token-identity asserts compare
    # numerically-different-but-equivalent paths (gather vs kernel,
    # k+1-query verify vs 1-query decode) whose bf16 argmax tie-flips
    # are noise, not bugs.
    model, cfg = make_gpt("tiny", dropout_rate=0.0, max_seq_len=128,
                          dtype=jnp.float32)
    rng = np.random.default_rng(1)
    params = model.init({"params": jax.random.PRNGKey(0),
                         "dropout": jax.random.PRNGKey(1)},
                        {"input_ids": np.zeros((1, 8), np.int32)})["params"]

    def build(**overrides):
        return deepspeed_tpu.init_serving(
            model, params=params, dtype=jnp.float32,
            config={"serving": {**SERVING_BENCH_CFG, **overrides},
                    "telemetry": {"enabled": True, "dir": ".",
                                  "metrics": {"sinks": ["memory"]},
                                  "trace": {"enabled": False}}})

    prompts = [rng.integers(0, cfg.vocab_size,
                            (int(rng.integers(6, 48)),)).tolist()
               for _ in range(8)]
    outs = [int(rng.integers(16, 40)) for _ in range(8)]

    def run(srv):
        # warmup (compiles off the clock), then the timed trace
        for p in prompts:
            srv.submit(p, 2)
        srv.run_until_complete()
        srv.results.clear()
        srv._decode_tokens, srv._decode_sec = 0, 0.0
        # spec counters too: warmup runs at max_new_tokens=2 truncate
        # accepts and would drag the reported accept rate down
        srv.stats.update(decode_steps=0, spec_rounds=0, spec_proposed=0,
                         spec_accepted=0, spec_new_tokens=0)
        for p, n in zip(prompts, outs):
            srv.submit(p, n)
        res = srv.run_until_complete()
        toks = [res[r]["tokens"] for r in sorted(res)]
        ms = 1e3 * srv._decode_sec / max(1, srv.stats["decode_steps"])
        return toks, ms, srv

    rows = {}
    toks_off, ms_off, _ = run(build())
    toks_on, ms_on, _ = run(build(decode_attention="kernel"))
    assert toks_on == toks_off, "kernel decode diverged from gather"
    rows["decode_step_gather_ms"] = round(ms_off, 3)
    rows["decode_step_kernel_ms"] = round(ms_on, 3)

    # cold vs warm-head TTFT: one cold prefill caches a 96-token head,
    # every later request adopts it and prefills only its 4-token tail.
    # A slightly wider model than the trace above so prompt compute (the
    # thing prefix reuse removes) dominates dispatch overhead on CPU;
    # requests are submitted one at a time so TTFT measures prefill, not
    # queue wait behind another row's decode.
    wmodel, wcfg = make_gpt("tiny", dropout_rate=0.0, max_seq_len=256,
                            hidden_size=128, num_layers=3, num_heads=4,
                            dtype=jnp.float32)
    wparams = wmodel.init(
        {"params": jax.random.PRNGKey(0), "dropout": jax.random.PRNGKey(1)},
        {"input_ids": np.zeros((1, 8), np.int32)})["params"]
    srv = deepspeed_tpu.init_serving(
        wmodel, params=wparams, dtype=jnp.float32,
        config={"serving": {**SERVING_BENCH_CFG, "max_model_len": 240,
                            "prefix_cache": True},
                "telemetry": {"enabled": True, "dir": ".",
                              "metrics": {"sinks": ["memory"]},
                              "trace": {"enabled": False}}})
    head = rng.integers(0, wcfg.vocab_size, (96,)).tolist()
    warm = [head + rng.integers(0, wcfg.vocab_size, (4,)).tolist()
            for _ in range(7)]
    hist = srv.telemetry.registry.histogram("serving/ttft_ms")
    srv.submit(warm[0], 2)                    # bucket warmup (compile)
    srv.run_until_complete()
    srv.submit(warm[1], 2)                    # tail-program warmup
    srv.run_until_complete()
    # cold: full prefill, re-measured with the cache cleared between
    # runs (median of 3 — a single observation is noise-prone on CPU);
    # the last run leaves the head registered for the warm half
    hist.reset()
    for _ in range(3):
        srv.prefix_cache.clear()
        srv.submit(warm[0], 4)
        srv.run_until_complete()
    rows["cold_ttft_ms"] = round(hist.percentile(50), 3)
    hist.reset()
    for p in warm[2:]:                        # warm: tail prefill only
        srv.submit(p, 4)
        srv.run_until_complete()
    assert srv.prefix_cache.hits >= len(warm) - 2
    rows["warm_ttft_p50_ms"] = round(hist.percentile(50), 3)

    # speculative decoding: accept rate + effective tokens per verify
    toks_spec, _ms, srv = run(build(
        speculative={"enabled": True, "k": 4}))
    assert toks_spec == toks_off, "speculative decode diverged from greedy"
    st = srv.stats
    rows["spec_accept_rate"] = round(
        st["spec_accepted"] / max(1, st["spec_proposed"]), 4)
    rows["spec_tokens_per_step"] = round(
        st["spec_new_tokens"] / max(1, st["spec_rounds"]), 3)
    return rows


def bench_serving_overload(n_requests=24):
    """Serving overload A/B (docs/SERVING.md "Serving under failure"):
    the same burst trace — offered load well past the 4-slot engine's
    capacity — with shedding off (everything queues; tail TTFT collapses
    under queue wait) vs the admission controller on per
    SERVING_RESILIENCE_CFG (overflow sheds at submit; admitted requests
    keep their TTFT). Returns shed fraction + admitted TTFT p99 rows for
    both arms."""
    import jax.numpy as jnp

    import deepspeed_tpu
    from deepspeed_tpu.models import make_gpt

    model, cfg = make_gpt("tiny", dropout_rate=0.0, max_seq_len=128,
                          dtype=jnp.float32)
    rng = np.random.default_rng(3)
    params = model.init({"params": jax.random.PRNGKey(0),
                         "dropout": jax.random.PRNGKey(1)},
                        {"input_ids": np.zeros((1, 8), np.int32)})["params"]
    prompts = [rng.integers(0, cfg.vocab_size,
                            (int(rng.integers(6, 48)),)).tolist()
               for _ in range(n_requests)]
    outs = [int(rng.integers(16, 40)) for _ in range(n_requests)]

    def run(resilient):
        scfg = dict(SERVING_BENCH_CFG)
        if resilient:
            scfg["resilience"] = dict(SERVING_RESILIENCE_CFG)
        srv = deepspeed_tpu.init_serving(
            model, params=params, dtype=jnp.float32,
            config={"serving": scfg,
                    "telemetry": {"enabled": True, "dir": ".",
                                  "metrics": {"sinks": ["memory"]},
                                  "trace": {"enabled": False}}})
        # warmup: compile every prefill bucket + decode off the clock
        seen = set()
        for p in prompts:
            b = srv._bucket_of(len(p))
            if b not in seen:
                seen.add(b)
                srv.submit(p, 2)
        srv.run_until_complete()
        srv.results.clear()
        hist = srv.telemetry.registry.histogram("serving/ttft_ms")
        hist.reset()
        for p, n in zip(prompts, outs):      # the burst: all at once
            srv.submit(p, n)
        res = srv.run_until_complete()
        shed = sum(1 for r in res.values() if r.get("status") == "shed")
        ttft_p99 = hist.percentile(99)        # admitted requests only:
        srv.close()                           # shed rows never observe
        return shed / len(res), ttft_p99

    shed_off, ttft_off = run(resilient=False)
    shed_on, ttft_on = run(resilient=True)
    assert shed_off == 0.0, "shedding happened with resilience off"
    return {
        "overload_shed_frac_off": round(shed_off, 4),
        "overload_shed_frac_on": round(shed_on, 4),
        "overload_admitted_ttft_p99_off_ms": round(ttft_off, 2),
        "overload_admitted_ttft_p99_on_ms": round(ttft_on, 2),
    }


def bench_serving_chunked():
    """Chunked-prefill admission A/B (docs/SERVING.md "Chunked prefill
    admission"): the same bursty trace — a burst of prompts whose lengths
    span several prefill buckets — served by the bucketed per-bucket
    prefill programs vs the single ragged mixed program. Cold engines on
    both sides: the bucketed path pays one compile per bucket it meets
    INSIDE the burst's TTFT window, the chunked path compiles its one
    mixed program once, which is the headline latency win on any
    backend. Outputs are asserted token-identical (same greedy trace).
    Rows: mean decode/mixed step wall ms + TTFT p99 per mode."""
    import jax.numpy as jnp

    import deepspeed_tpu
    from deepspeed_tpu.models import make_gpt

    model, cfg = make_gpt("tiny", dropout_rate=0.0, max_seq_len=128,
                          dtype=jnp.float32)
    rng = np.random.default_rng(5)
    params = model.init({"params": jax.random.PRNGKey(0),
                         "dropout": jax.random.PRNGKey(1)},
                        {"input_ids": np.zeros((1, 8), np.int32)})["params"]
    # Bursty: lengths span >= 3 prefill buckets, all submitted at once.
    lens = [10, 20, 40, 70, 12, 44, 22, 68]
    prompts = [rng.integers(0, cfg.vocab_size, (n,)).tolist()
               for n in lens]
    outs = [int(rng.integers(8, 20)) for _ in lens]

    def run(chunked):
        srv = deepspeed_tpu.init_serving(
            model, params=params, dtype=jnp.float32,
            config={"serving": {
                        **SERVING_BENCH_CFG,
                        "chunked_prefill": {"enabled": chunked,
                                            "token_budget": 32}},
                    "telemetry": {"enabled": True, "dir": ".",
                                  "metrics": {"sinks": ["memory"]},
                                  "trace": {"enabled": False}}})
        for p, n in zip(prompts, outs):
            srv.submit(p, n)
        res = srv.run_until_complete()
        toks = [res[r]["tokens"] for r in sorted(res)]
        ms = 1e3 * srv._decode_sec / max(1, srv.stats["decode_steps"])
        p99 = srv.telemetry.registry.histogram(
            "serving/ttft_ms").percentile(99)
        return toks, ms, p99

    toks_b, ms_b, p99_b = run(False)
    toks_c, ms_c, p99_c = run(True)
    assert toks_c == toks_b, "chunked admission diverged from bucketed"
    return {
        "mixed_step_bucketed_ms": round(ms_b, 3),
        "mixed_step_chunked_ms": round(ms_c, 3),
        "ttft_p99_bucketed_ms": round(p99_b, 2),
        "ttft_p99_chunked_ms": round(p99_c, 2),
    }


def bench_fused_optimizer():
    """Fused blockwise Adam A/B (docs/PERFORMANCE.md "Kernel tier round
    2"): jitted XLA elementwise update chain vs the single-pass Pallas
    kernel over the same ~1M-element parameter tree, single device.
    Trajectories are asserted to match (the kernel bit-matches the op
    order, tests/test_fused_update.py); off-TPU the kernel runs through
    the Pallas interpreter, so this row exists for a TPU round to show
    the HBM round-trip win. Rows: mean optimizer step wall ms per
    mode."""
    import jax.numpy as jnp

    from deepspeed_tpu.ops.adam.fused_adam import FusedAdam
    from deepspeed_tpu.ops.adam.fused_update import fused_adam_apply

    rng = np.random.default_rng(3)
    params = {
        "dense": jnp.asarray(rng.standard_normal((1024, 768)), jnp.float32),
        "embed": jnp.asarray(rng.standard_normal((512, 512)), jnp.float32),
        "bias": jnp.asarray(rng.standard_normal((768,)), jnp.float32),
    }
    grads = jax.tree_util.tree_map(
        lambda p: jnp.asarray(rng.standard_normal(p.shape), p.dtype) * 0.01,
        params)
    opt = FusedAdam(lr=1e-3, weight_decay=0.01, adamw_mode=True)
    state = opt.init(params)

    xla_step = jax.jit(lambda g, s, p: opt.update(g, s, p, lr=1e-3))
    fused_step = jax.jit(
        lambda g, s, p: fused_adam_apply(opt, g, s, p, lr=1e-3))

    def time_step(fn):
        p, s = params, state
        p, s = fn(grads, s, p)                    # compile off the clock
        jax.block_until_ready(p)
        t0 = time.time()
        reps = 5
        for _ in range(reps):
            p, s = fn(grads, s, p)
        jax.block_until_ready(p)
        return 1e3 * (time.time() - t0) / reps, p

    ms_xla, p_xla = time_step(xla_step)
    ms_fused, p_fused = time_step(fused_step)
    err = max(float(jnp.max(jnp.abs(a - b)))
              for a, b in zip(jax.tree_util.tree_leaves(p_xla),
                              jax.tree_util.tree_leaves(p_fused)))
    assert err < 1e-5, f"fused update trajectory diverged ({err})"
    return {
        "optimizer_step_xla_ms": round(ms_xla, 3),
        "optimizer_step_fused_ms": round(ms_fused, 3),
    }


def _section_rows(result, name, **rows):
    """Record one section's metric rows under ``result["sections"]`` — the
    schema ``tools/bench_gate.py`` compares against the committed
    baseline (the flat top-level keys stay for the driver's one-line
    record; this block is the gate's contract). A section whose timing
    lost windows to a transient failure is stamped ``partial``: the
    evidence lands, but run_section keeps rc=1 semantics for it."""
    row = {k: v for k, v in rows.items() if v is not None}
    if _TIMING_PARTIAL["flag"]:
        _TIMING_PARTIAL["flag"] = False
        row["partial"] = 1
    result.setdefault("sections", {})[name] = row


def _flush_partial(result):
    try:
        tmp = PARTIAL_PATH + ".tmp"
        with open(tmp, "w") as f:
            json.dump(result, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, PARTIAL_PATH)
    except OSError as e:  # a full disk must not kill the bench itself
        log(f"[bench] WARNING: partial flush failed: {e}")


def _is_transient(e) -> bool:
    """Worth a retry? Tunnel/infra failures (the round-4 killer was a
    dropped remote_compile connection surfacing as JaxRuntimeError) — not
    deterministic bugs, whose retry would just repeat a multi-minute
    compile to fail identically. Deterministic runtime errors that ALSO
    surface as JaxRuntimeError (HBM OOM) are screened by message."""
    msg = str(e).lower()
    if any(s in msg for s in ("resource_exhausted", "out of memory", "oom",
                              "no such file")):
        return False
    if isinstance(e, FileNotFoundError):
        return False
    try:
        from jax.errors import JaxRuntimeError
        if isinstance(e, JaxRuntimeError):
            return True
    except ImportError:
        pass
    if isinstance(e, (ConnectionError, TimeoutError, OSError)):
        return True
    return any(s in msg for s in ("remote_compile", "read body", "tunnel",
                                  "connection reset", "connection closed",
                                  "deadline", "unavailable"))


def _record_headroom(name, result):
    """Headroom (tightest device's bytes_limit − peak,
    telemetry/memory.py) recorded AFTER each section. The peak is the
    process-lifetime high-water mark (jax never resets it), so each
    value is the margin left after everything run SO FAR — monotone
    non-increasing across sections; the last section's value is the
    run's overall minimum margin. None on backends without
    memory_stats (CPU); never fails the section."""
    try:
        from deepspeed_tpu.telemetry.memory import min_headroom_bytes
        result.setdefault("peak_headroom_bytes", {})[name] = \
            min_headroom_bytes()
    except Exception as e:  # noqa: BLE001 — accounting must not kill bench
        log(f"[bench] WARNING: headroom record failed for {name!r}: {e}")


def run_section(name, fn, result, retries=1):
    """Run one bench section; on a transient failure (tunnel
    JaxRuntimeError & co — see ``_is_transient``) retry once from scratch:
    sections are self-contained, so a retry just re-traces and re-compiles.
    A section that fails terminally records its error and the bench moves
    on: partial evidence beats none."""
    for attempt in range(retries + 1):
        # A flag left by a PREVIOUS section/attempt that errored before
        # recording its row must not stamp this attempt's rows.
        _TIMING_PARTIAL["flag"] = False
        try:
            fn()
            _record_headroom(name, result)
            _flush_partial(result)
            if result.get("sections", {}).get(name, {}).get("partial"):
                # Partial evidence recorded, but the section is NOT
                # green: keep the backend-init-style rc=1 semantics so
                # the driver's rc log stays honest about the round.
                result.setdefault("errors", []).append(
                    f"{name}: partial (transient mid-window failure)")
                return False
            return True
        except Exception as e:  # noqa: BLE001 — isolate every section
            log(f"[bench] section {name!r} attempt {attempt + 1} failed: "
                f"{type(e).__name__}: {e}")
            log(traceback.format_exc())
            result.setdefault("errors", []).append(
                f"{name}: {type(e).__name__}: {e}")
            _flush_partial(result)
            if not _is_transient(e):
                return False
    return False


def main():
    # Evict any stale partial from a previous run FIRST — before the
    # backend probe, which is itself a failure mode (round-5: a tunnel
    # that never comes up dies in jax.devices()). A backend-init failure
    # must take the same zero-row rc=1 path as an all-sections failure and
    # must never leave a previous run's BENCH_partial.json masquerading as
    # this run's record.
    result = {
        "metric": "pretrain throughput (backend unavailable)",
        "value": None,
        "unit": "samples/sec/chip",
        "vs_baseline": None,
    }
    _flush_partial(result)
    try:
        dev = jax.devices()[0]
        platform = dev.platform
    except Exception as e:  # noqa: BLE001 — isolate backend init like sections
        log(f"[bench] backend init failed: {type(e).__name__}: {e}")
        log(traceback.format_exc())
        result.setdefault("errors", []).append(
            f"backend-init: {type(e).__name__}: {e}")
        _flush_partial(result)
        print(json.dumps(result))
        sys.exit(1)
    on_tpu = platform == "tpu"
    peak = peak_tflops(getattr(dev, "device_kind", ""), dtype="bfloat16")
    n_chips_all = len(jax.devices())
    # Environment block: the conditions the rows were measured under, so
    # numbers stay comparable across PRs. telemetry is explicitly "off" —
    # none of the bench configs enable the telemetry block, so no sync'd
    # spans or per-step gauges perturb the timed windows; a future PR that
    # benches with telemetry on must say so here. goodput rides telemetry
    # (telemetry/goodput.py), so it is off too — its accountant is pure
    # host clock reads, but the env block records the whole config anyway.
    result["environment"] = {
        "platform": platform,
        "device_kind": getattr(dev, "device_kind", ""),
        "devices": n_chips_all,
        "jax": jax.__version__,
        "telemetry": "off",
        "goodput": "off",
        # Fleet telemetry (telemetry/fleet.py) would add a per-flush
        # collective + host fetch; the timed windows run without it, and
        # a future fleet-on BENCH round must record its fleet block here
        # so rows stay attributable.
        "fleet": "off",
        # Device-time observatory (telemetry/devicetime.py) off: no
        # scheduled jax.profiler captures perturb the timed windows; a
        # future BENCH round capturing mid-bench must record its
        # devicetime block here so rows stay attributable.
        "devicetime": "off",
        # Memory observatory (telemetry/memory.py) off: no per-step
        # headroom gauges and no attribution AOT compile in the timed
        # windows. Per-round peak headroom is still recorded under
        # "peak_headroom_bytes" (a free post-section memory_stats read)
        # so capacity regressions show up next to the throughput rows.
        "memory": "off",
        # Numerics observatory (telemetry/numerics.py) off: the in-
        # program per-group stat reductions would ride inside the timed
        # step programs; a future BENCH round measuring with numerics on
        # must record its block here so rows stay attributable.
        "numerics": "off",
        # Live elasticity (resilience/elastic.py) off: no SIGTERM handler
        # and no step-boundary coordinator checks in the timed windows
        # (the contract says off is free — bit-identical lowered step —
        # but the env block records the whole config anyway).
        "elasticity": "off",
        "peak_tflops_per_chip": peak,
        # Gradient-sync strategy the rows were measured under
        # (comm/grad_sync.py): none of the training-section configs set
        # a comm block, so the implicit full-precision path is timed —
        # overlap_grad_sync included, since overlap only exists inside
        # the hierarchical strategy. The comm_overlap section below
        # measures the overlapped schedule explicitly and records its
        # own config in its rows. A future PR benching the training
        # sections with hierarchical sync on must record its comm block
        # here so BENCH_*.json rows stay attributable.
        "comm": {"hierarchical": "off", "overlap_grad_sync": "off"},
        # ZeRO++ weight path (zero_optimization.zeropp): the training
        # sections above run with the block OFF (bit-identical implicit
        # param path); the zeropp A/B section below measures the
        # explicit quantized weight gather and records its own config
        # in its rows. A future PR benching the training sections with
        # qwZ/hpZ on must record its zeropp block here so BENCH_*.json
        # rows stay attributable.
        "zeropp": {"quantized_weights": "off", "hpz": "off"},
        # Autotuning (autotuning/; docs/PERFORMANCE.md "Autotuning") off:
        # every training section above times the config it declares — no
        # startup search swaps knobs under a timed window. The autotune
        # A/B section below runs the search explicitly and records the
        # adopted candidate in its own rows, so a tuned baseline adopted
        # via tools/bench_gate.py --update-baseline stays attributable.
        "autotuning": "off",
        # MoE (moe/; docs/MOE.md) off on every training section above:
        # no `moe` config block, so the lowered steps are bit-identical
        # to the pre-MoE programs (the zero-overhead contract,
        # tests/test_moe.py). The moe_gpt A/B section below is the only
        # MoE workload and records its dispatch mode in its own rows.
        "moe": "off",
        # Serving-section config (docs/SERVING.md): the continuous-
        # batching rows below were measured under exactly this block.
        # Its memory-sink telemetry is scoped to the serving engine and
        # never touches the training sections' timed windows.
        "serving": dict(SERVING_BENCH_CFG),
        # Request observatory (telemetry/requests.py) behind the serving
        # section's tpot_p50_ms/tpot_p99_ms/e2e_p99_ms rows.
        "requests": dict(SERVING_REQUESTS_CFG),
        # Serving resilience (serving/resilience.py) behind the overload
        # A/B rows; every other serving row runs with resilience off.
        "serving_resilience": dict(SERVING_RESILIENCE_CFG),
        # Kernel tier round 2 (docs/PERFORMANCE.md): both kernels OFF on
        # every section except their own A/B rows — chunked admission in
        # the serving section's chunked_* rows, the fused Adam pass in
        # the fused_optimizer section. Off is the zero-overhead default
        # (bit-identical lowered programs, tests/test_chunked_prefill.py
        # / test_fused_update.py).
        "chunked_prefill": "off",
        "fused_update": "off",
    }

    if on_tpu:
        steps, warmup = 10, 2
    else:
        steps, warmup = 3, 1

    result["metric"] = (f"BERT-{'large' if on_tpu else 'tiny'} seq128 "
                        f"ZeRO-2 pretrain throughput ({platform})")
    _flush_partial(result)

    def sec_bert128():
        t0 = time.time()
        sps128, tf128, n_params, sps128_med, flops, dt = bench_bert(
            seq=128 if on_tpu else 64, micro_bs=32 if on_tpu else 8,
            gas=8 if on_tpu else 1, steps=steps, warmup=warmup, on_tpu=on_tpu)
        mfu128 = compute_mfu(flops, dt, n_chips=n_chips_all,
                             peak_tflops_per_chip=peak)
        log(f"[bench] BERT-large seq128: {sps128:.1f} samples/s/chip, "
            f"{tf128:.1f} TFLOP/s, MFU {mfu128:.1%} "
            f"({n_params / 1e6:.0f}M params, "
            f"setup+run {time.time() - t0:.0f}s)")
        result["value"] = round(sps128, 2)
        result["vs_baseline"] = round(sps128 / BASELINE_BERT_SEQ128, 4)
        result["tflops"] = round(tf128, 1)
        result["mfu"] = round(mfu128, 4)
        # median-of-windows companion (ADVICE r3): drift-inclusive view of
        # the same run; `value`/`vs_baseline` stay best-of-windows.
        result["value_median_window"] = round(sps128_med, 2)
        _section_rows(result, "bert128", samples_per_sec=result["value"],
                      tflops=result["tflops"], mfu=result["mfu"])

    def sec_bert512():
        t0 = time.time()
        sps512, tf512, _, sps512_med, flops, dt = bench_bert(
            seq=512, micro_bs=8, gas=8, steps=steps, warmup=warmup,
            on_tpu=on_tpu)
        mfu512 = compute_mfu(flops, dt, n_chips=n_chips_all,
                             peak_tflops_per_chip=peak)
        log(f"[bench] BERT-large seq512: {sps512:.1f} samples/s/chip, "
            f"{tf512:.1f} TFLOP/s, MFU {mfu512:.1%} "
            f"({time.time() - t0:.0f}s)")
        result["bert_seq512_samples_per_sec"] = round(sps512, 2)
        result["bert_seq512_vs_baseline"] = round(
            sps512 / BASELINE_BERT_SEQ512, 4)
        result["bert_seq512_median_window"] = round(sps512_med, 2)
        _section_rows(result, "bert512",
                      samples_per_sec=result["bert_seq512_samples_per_sec"],
                      mfu=round(mfu512, 4))

    def sec_gpt2():
        t0 = time.time()
        gpt2_tps, gpt2_tf, gpt2_tps_med, flops, dt = bench_gpt2(
            steps, warmup, on_tpu)
        gpt2_mfu = compute_mfu(flops, dt, n_chips=n_chips_all,
                               peak_tflops_per_chip=peak)
        log(f"[bench] GPT-2 seq512: {gpt2_tps:.0f} tokens/s/chip, "
            f"{gpt2_tf:.1f} TFLOP/s, MFU {gpt2_mfu:.1%} "
            f"({time.time() - t0:.0f}s)")
        result["gpt2_tokens_per_sec"] = round(gpt2_tps, 0)
        result["gpt2_vs_baseline"] = round(gpt2_tps / BASELINE_GPT2_TOKENS, 4)
        result["gpt2_median_window"] = round(gpt2_tps_med, 0)
        result["gpt2_mfu"] = round(gpt2_mfu, 4)
        _section_rows(result, "gpt2",
                      tokens_per_sec=result["gpt2_tokens_per_sec"],
                      mfu=result["gpt2_mfu"])

    def sec_gpt2_dropout():
        # Dropout-on variant (r2 VERDICT task 4 "done" criterion): real
        # pretraining configs keep the flash path via in-kernel dropout.
        t0 = time.time()
        gpt2_do_tps, gpt2_do_tf, _, flops, dt = bench_gpt2(
            steps, warmup, on_tpu, dropout_rate=0.1)
        do_mfu = compute_mfu(flops, dt, n_chips=n_chips_all,
                             peak_tflops_per_chip=peak)
        log(f"[bench] GPT-2 seq512 dropout=0.1: {gpt2_do_tps:.0f} "
            f"tokens/s/chip, {gpt2_do_tf:.1f} TFLOP/s, MFU "
            f"{do_mfu:.1%} ({time.time() - t0:.0f}s)")
        result["gpt2_dropout_tokens_per_sec"] = round(gpt2_do_tps, 0)
        result["gpt2_dropout_mfu"] = round(do_mfu, 4)
        _section_rows(result, "gpt2_dropout",
                      tokens_per_sec=result["gpt2_dropout_tokens_per_sec"],
                      mfu=result["gpt2_dropout_mfu"])

    def sec_long():
        t0 = time.time()
        long_dense = bench_gpt2_long(steps=4, warmup=1, sparse=False)
        result["gpt2_seq16k_dense_tokens_per_sec"] = round(long_dense, 0)
        _flush_partial(result)
        long_sparse = bench_gpt2_long(steps=4, warmup=1, sparse=True)
        log(f"[bench] GPT-2 seq16384: dense {long_dense:.0f} tok/s, "
            f"bigbird {long_sparse:.0f} tok/s "
            f"({long_sparse / long_dense:.2f}x, {time.time() - t0:.0f}s)")
        result["gpt2_seq16k_bigbird_tokens_per_sec"] = round(long_sparse, 0)
        result["gpt2_seq16k_sparse_speedup"] = round(
            long_sparse / long_dense, 3)
        _section_rows(
            result, "long16k",
            dense_tokens_per_sec=result["gpt2_seq16k_dense_tokens_per_sec"],
            bigbird_tokens_per_sec=result[
                "gpt2_seq16k_bigbird_tokens_per_sec"],
            sparse_speedup=result["gpt2_seq16k_sparse_speedup"])

    def sec_inference():
        t0 = time.time()
        tps1 = bench_inference(batch=1)
        result["gpt2_generate_b1_tokens_per_sec"] = round(tps1, 1)
        _flush_partial(result)
        tps8 = bench_inference(batch=8)
        log(f"[bench] GPT-2 generate (KV cache, prompt 128 + 128 new): "
            f"b1 {tps1:.1f} tok/s, b8 {tps8:.1f} tok/s "
            f"({time.time() - t0:.0f}s)")
        result["gpt2_generate_b8_tokens_per_sec"] = round(tps8, 1)
        _section_rows(
            result, "inference",
            b1_tokens_per_sec=result["gpt2_generate_b1_tokens_per_sec"],
            b8_tokens_per_sec=result["gpt2_generate_b8_tokens_per_sec"])

    def sec_serving():
        # Continuous-batching serving row (tiny GPT, CPU-runnable): the
        # serving machinery's offline throughput + TTFT SLO percentiles.
        t0 = time.time()
        tps, p50, p99, occ, tpot50, tpot99, e2e99 = bench_serving()
        log(f"[bench] serving (tiny GPT, {SERVING_BENCH_CFG['max_batch_size']}"
            f" slots): {tps:.1f} tok/s, TTFT p50 {p50:.1f} ms / p99 "
            f"{p99:.1f} ms, TPOT p50 {tpot50:.1f} ms / p99 {tpot99:.1f} ms, "
            f"e2e p99 {e2e99:.1f} ms, occupancy {occ:.1%} "
            f"({time.time() - t0:.0f}s)")
        result["serving_tokens_per_sec"] = round(tps, 1)
        result["serving_ttft_p50_ms"] = round(p50, 2)
        result["serving_ttft_p99_ms"] = round(p99, 2)
        result["serving_tpot_p50_ms"] = round(tpot50, 3)
        result["serving_tpot_p99_ms"] = round(tpot99, 3)
        result["serving_e2e_p99_ms"] = round(e2e99, 2)
        result["serving_mean_occupancy"] = round(occ, 4)
        # decode fast path A/B (docs/SERVING.md): gather-vs-kernel decode
        # step, cold-vs-warm-head TTFT, speculative accept evidence — all
        # on the same trace, token-identity asserted inside.
        t0 = time.time()
        fp = bench_serving_fastpath()
        log(f"[bench] serving fast path: decode gather "
            f"{fp['decode_step_gather_ms']:.2f} ms vs kernel "
            f"{fp['decode_step_kernel_ms']:.2f} ms; TTFT cold "
            f"{fp['cold_ttft_ms']:.1f} ms vs warm p50 "
            f"{fp['warm_ttft_p50_ms']:.1f} ms; spec accept "
            f"{fp['spec_accept_rate']:.1%}, "
            f"{fp['spec_tokens_per_step']:.2f} tok/verify "
            f"({time.time() - t0:.0f}s)")
        for key, val in fp.items():
            result[f"serving_{key}"] = val
        # overload A/B (docs/SERVING.md "Serving under failure"):
        # offered load > capacity, shedding off vs on.
        t0 = time.time()
        ov = bench_serving_overload()
        log(f"[bench] serving overload: shed "
            f"{ov['overload_shed_frac_off']:.0%} off vs "
            f"{ov['overload_shed_frac_on']:.0%} on; admitted TTFT p99 "
            f"{ov['overload_admitted_ttft_p99_off_ms']:.1f} ms off vs "
            f"{ov['overload_admitted_ttft_p99_on_ms']:.1f} ms on "
            f"({time.time() - t0:.0f}s)")
        for key, val in ov.items():
            result[f"serving_{key}"] = val
        # chunked-prefill admission A/B (docs/SERVING.md "Chunked
        # prefill admission"): bursty multi-bucket trace, bucketed
        # per-bucket programs vs the one ragged mixed program —
        # token-identity asserted inside.
        t0 = time.time()
        ck = bench_serving_chunked()
        log(f"[bench] serving chunked prefill: step "
            f"{ck['mixed_step_bucketed_ms']:.2f} ms bucketed vs "
            f"{ck['mixed_step_chunked_ms']:.2f} ms chunked; TTFT p99 "
            f"{ck['ttft_p99_bucketed_ms']:.1f} ms vs "
            f"{ck['ttft_p99_chunked_ms']:.1f} ms "
            f"({time.time() - t0:.0f}s)")
        for key, val in ck.items():
            result[f"serving_{key}"] = val
        # tpot/e2e rows are `*_ms`, so bench_gate treats them as
        # lower-is-better automatically (latency regresses upward).
        _section_rows(result, "serving",
                      tokens_per_sec=result["serving_tokens_per_sec"],
                      ttft_p50_ms=result["serving_ttft_p50_ms"],
                      ttft_p99_ms=result["serving_ttft_p99_ms"],
                      tpot_p50_ms=result["serving_tpot_p50_ms"],
                      tpot_p99_ms=result["serving_tpot_p99_ms"],
                      e2e_p99_ms=result["serving_e2e_p99_ms"],
                      mean_occupancy=result["serving_mean_occupancy"],
                      **fp, **ov, **ck)

    def gpt_ab_times(gas, make_config):
        # Shared 2-slice tiny-GPT A/B harness for the comm_overlap and
        # zeropp sections: build the model once, then time an off/on
        # engine pair — make_config(variant) supplies each variant's
        # config block on top of the common batch/optimizer base.
        import deepspeed_tpu
        from deepspeed_tpu.models import make_gpt
        from deepspeed_tpu.parallel.mesh import build_mesh

        import jax.numpy as jnp

        # micro_bs 1 per chip: the global microbatch is the chip count
        # (put_batch shards over dcn x data).
        seq, bs = 64 if on_tpu else 32, n_chips_all
        model, cfg = make_gpt(
            "tiny", dropout_rate=0.0,
            dtype=jnp.bfloat16 if on_tpu else jnp.float32,
            max_seq_len=max(seq, 128))
        rng = np.random.default_rng(0)
        ids = rng.integers(0, cfg.vocab_size, (gas, bs, seq),
                           dtype=np.int32)
        params = model.init({"params": jax.random.PRNGKey(0),
                             "dropout": jax.random.PRNGKey(1)},
                            {"input_ids": ids[0]})["params"]
        times = {}
        for variant in ("off", "on"):
            engine, _, _, _ = deepspeed_tpu.initialize(
                model=model, params=params, mesh=build_mesh(slices=2),
                config={
                    "train_micro_batch_size_per_gpu": 1,
                    "gradient_accumulation_steps": gas,
                    "optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
                    **make_config(variant),
                })
            dt, _ = time_train_batches(engine, {"input_ids": ids},
                                       max(steps, 2), warmup, windows=2)
            times[variant] = dt / max(steps, 2)
            del engine
        return times

    def sec_comm_overlap():
        # Overlapped gradient sync A/B (docs/PERFORMANCE.md "Overlapped
        # gradient sync"): tiny GPT on a 2-slice mesh, hierarchical int8
        # sync with overlap off vs on. On TPU the overlap hides the DCN
        # wire time (step time drops); on CPU the section is still a
        # schedule-correctness row. step-time rows are *_ms so the gate
        # treats upward drift as regression.
        t0 = time.time()
        times = gpt_ab_times(4, lambda variant: {
            "zero_optimization": {"stage": 2},
            "comm": {"hierarchical": "on", "dcn_quant_bits": 8,
                     "quant_block_size": 256,
                     "overlap_grad_sync": variant},
        })
        speedup = times["off"] / times["on"] if times["on"] else 0.0
        log(f"[bench] comm overlap A/B (tiny GPT, 2-slice int8): "
            f"off {times['off'] * 1e3:.1f} ms/step, on "
            f"{times['on'] * 1e3:.1f} ms/step ({speedup:.2f}x, "
            f"{time.time() - t0:.0f}s)")
        result["comm_overlap_step_speedup"] = round(speedup, 3)
        _section_rows(
            result, "comm_overlap",
            step_time_overlap_off_ms=round(times["off"] * 1e3, 3),
            step_time_overlap_on_ms=round(times["on"] * 1e3, 3),
            overlap_step_speedup=round(speedup, 3))

    def sec_zeropp():
        # ZeRO++ weight path A/B (docs/PERFORMANCE.md "ZeRO++ weight
        # path"): tiny GPT stage-3 on a 2-slice mesh, zeropp off vs
        # qwZ-int8 + hpZ. On TPU the quantized gather cuts the param
        # all-gather wire time; on CPU the section is a schedule-
        # correctness row. step-time rows are *_ms so the gate treats
        # upward drift as regression. The baseline adopts this section
        # via the documented --update-baseline green-round flow
        # (tools/bench_gate.py treats a new section as informational
        # until then).
        t0 = time.time()
        times = gpt_ab_times(2, lambda variant: {
            "zero_optimization": {
                "stage": 3, "stage3_param_persistence_threshold": 0,
                **({"zeropp": {"quantized_weights": "int8", "hpz": "on",
                               "quant_block_size": 256}}
                   if variant == "on" else {}),
            },
        })
        speedup = times["off"] / times["on"] if times["on"] else 0.0
        log(f"[bench] zeropp A/B (tiny GPT stage-3, 2-slice): off "
            f"{times['off'] * 1e3:.1f} ms/step, qwZ-int8+hpZ "
            f"{times['on'] * 1e3:.1f} ms/step ({speedup:.2f}x, "
            f"{time.time() - t0:.0f}s)")
        result["zeropp_step_speedup"] = round(speedup, 3)
        _section_rows(
            result, "zeropp",
            step_time_zeropp_off_ms=round(times["off"] * 1e3, 3),
            step_time_zeropp_on_ms=round(times["on"] * 1e3, 3),
            zeropp_step_speedup=round(speedup, 3))

    def sec_autotune():
        # Tuned-vs-default A/B (docs/PERFORMANCE.md "Autotuning"): tiny
        # GPT on a 2-slice mesh; the default engine times its declared
        # config, the tuned engine runs the startup search (micro x gas
        # re-split + the DCN quantization knobs) and times the adopted
        # one. The tuner trials the default too, so tuned <= default up
        # to timing noise — the gate's *_ms rows treat upward drift as
        # regression, and a green round can adopt the tuned row as
        # baseline via the documented --update-baseline flow (the gate
        # treats the new section as informational until then).
        import deepspeed_tpu
        from deepspeed_tpu.models import make_gpt
        from deepspeed_tpu.parallel.mesh import build_mesh

        import jax.numpy as jnp

        t0 = time.time()
        seq, gas0 = 64 if on_tpu else 32, 4
        model, mcfg = make_gpt(
            "tiny", dropout_rate=0.0,
            dtype=jnp.bfloat16 if on_tpu else jnp.float32,
            max_seq_len=max(seq, 128))
        rng = np.random.default_rng(0)
        params = model.init(
            {"params": jax.random.PRNGKey(0),
             "dropout": jax.random.PRNGKey(1)},
            {"input_ids": np.zeros((2, seq), np.int32)})["params"]

        def make_batches(micro, gas):
            return {"input_ids": rng.integers(
                0, mcfg.vocab_size, (gas, micro, seq), dtype=np.int32)}

        base = {
            "train_micro_batch_size_per_gpu": 1,
            "gradient_accumulation_steps": gas0,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
            "zero_optimization": {"stage": 2},
        }
        times, adopted = {}, None
        for variant in ("default", "tuned"):
            cfg_v = dict(base)
            if variant == "tuned":
                cfg_v["autotuning"] = {
                    "micro_gas": [[1, gas0], [gas0, 1]],
                    "dcn_quant_bits": [8, 32],
                    "top_k": 3, "trial_steps": max(steps, 2),
                    "trial_warmup": warmup,
                }
            engine, _, _, _ = deepspeed_tpu.initialize(
                model=model, params=params, mesh=build_mesh(slices=2),
                config=cfg_v)
            if variant == "tuned":
                res = deepspeed_tpu.autotune(engine, make_batches)
                adopted = res["adopted"]["name"]
            batches = make_batches(
                engine.train_micro_batch_size_per_gpu * engine.dp_size,
                engine.gradient_accumulation_steps)
            dt, _ = time_train_batches(engine, batches, max(steps, 2),
                                       warmup, windows=2)
            times[variant] = dt / max(steps, 2)
            del engine
        speedup = (times["default"] / times["tuned"]
                   if times["tuned"] else 0.0)
        log(f"[bench] autotune A/B (tiny GPT, 2-slice): default "
            f"{times['default'] * 1e3:.1f} ms/step, tuned "
            f"{times['tuned'] * 1e3:.1f} ms/step ({speedup:.2f}x, "
            f"adopted '{adopted}', {time.time() - t0:.0f}s)")
        result["autotune_adopted"] = adopted
        result["autotune_step_speedup"] = round(speedup, 3)
        _section_rows(
            result, "autotune",
            step_time_default_ms=round(times["default"] * 1e3, 3),
            step_time_tuned_ms=round(times["tuned"] * 1e3, 3),
            autotune_step_speedup=round(speedup, 3))

    def sec_moe_gpt():
        # MoE GPT dispatch A/B (docs/MOE.md): tiny 4-expert GPT on a
        # data x expert=2 mesh, the SAME model timed under each dispatch
        # mode — einsum oracle vs slot-scatter vs explicit all-to-all
        # (moe/dispatch.py). The modes are numerically parity-tested
        # (tests/test_moe.py), so the rows are a pure schedule/layout
        # comparison; on CPU they are schedule-correctness rows. The
        # timed engines keep telemetry OFF (env block above); the
        # overflow row comes from one short untimed telemetry-on run,
        # and the wire row from the static dispatch-bytes model.
        import deepspeed_tpu
        from deepspeed_tpu.models import build_specs, make_gpt
        from deepspeed_tpu.models.gpt import gpt_partition_rules
        from deepspeed_tpu.parallel.mesh import build_mesh
        from deepspeed_tpu.telemetry.registry import InMemorySink

        import jax.numpy as jnp

        t0 = time.time()
        seq = 64 if on_tpu else 32
        experts = 4
        model, mcfg = make_gpt(
            "tiny", dropout_rate=0.0,
            dtype=jnp.bfloat16 if on_tpu else jnp.float32,
            max_seq_len=max(seq, 128), moe_experts=experts, moe_k=1,
            moe_layer_freq=2)
        rng = np.random.default_rng(0)
        mesh = build_mesh(data=-1, expert=2)
        dp = n_chips_all // 2
        # micro 2/chip: tokens (2*dp*seq) divide the dispatch grid
        # (data-like x expert = n_chips) for the all-to-all manual region
        ids = rng.integers(0, mcfg.vocab_size, (1, 2 * dp, seq),
                           dtype=np.int32)
        params = model.init({"params": jax.random.PRNGKey(0),
                             "dropout": jax.random.PRNGKey(1)},
                            {"input_ids": ids[0]})["params"]
        specs = build_specs(params, gpt_partition_rules(),
                            mesh_axes=dict(mesh.shape))

        def moe_engine(dispatch, telemetry=None):
            engine, _, _, _ = deepspeed_tpu.initialize(
                model=model, params=params, mesh=mesh,
                param_partition_specs=specs,
                config={
                    "train_micro_batch_size_per_gpu": 2,
                    "gradient_accumulation_steps": 1,
                    "optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
                    "zero_optimization": {"stage": 1},
                    "moe": {"enabled": True, "num_experts": experts,
                            "k": 1, "dispatch": dispatch},
                    **(telemetry or {}),
                })
            return engine

        times = {}
        for mode in ("einsum", "scatter", "alltoall"):
            engine = moe_engine(mode)
            dt, _ = time_train_batches(engine, {"input_ids": ids},
                                       max(steps, 2), warmup, windows=2)
            times[mode] = dt / max(steps, 2)
            del engine
        # Untimed stats run (scatter — the mode is irrelevant for the
        # routing stats): real overflow fraction off the moe/* gauges.
        import tempfile as _tempfile
        with _tempfile.TemporaryDirectory() as tdir:
            engine = moe_engine("scatter", telemetry={
                "telemetry": {"enabled": True, "dir": tdir},
                "steps_per_print": 1})
            sink = engine.telemetry.registry.add_sink(InMemorySink())
            for _ in range(2):
                engine.train_batch({"input_ids": ids})
            overflow = [r["value"] for r in sink.rows
                        if r["tag"] == "moe/capacity_overflow_frac"]
            del engine
        from deepspeed_tpu.moe.dispatch import modeled_dispatch_bytes_ici
        tokens = 2 * dp * seq
        capacity = max(4, int(np.ceil(tokens / experts * 1.25)))
        wire = modeled_dispatch_bytes_ici(
            num_experts=experts, capacity=capacity, hidden=mcfg.hidden_size,
            dtype=jnp.bfloat16 if on_tpu else jnp.float32, mesh=mesh)
        a2a_vs_scatter = (times["scatter"] / times["alltoall"]
                          if times["alltoall"] else 0.0)
        log(f"[bench] moe_gpt dispatch A/B (tiny {experts}-expert GPT, "
            f"expert=2): einsum {times['einsum'] * 1e3:.1f} ms/step, "
            f"scatter {times['scatter'] * 1e3:.1f} ms/step, alltoall "
            f"{times['alltoall'] * 1e3:.1f} ms/step "
            f"({a2a_vs_scatter:.2f}x vs scatter), overflow "
            f"{(overflow[-1] if overflow else 0):.3f}, modeled wire "
            f"{wire} B/layer ({time.time() - t0:.0f}s)")
        result["moe_gpt_alltoall_vs_scatter"] = round(a2a_vs_scatter, 3)
        _section_rows(
            result, "moe_gpt",
            step_time_einsum_ms=round(times["einsum"] * 1e3, 3),
            step_time_scatter_ms=round(times["scatter"] * 1e3, 3),
            step_time_alltoall_ms=round(times["alltoall"] * 1e3, 3),
            alltoall_vs_scatter_speedup=round(a2a_vs_scatter, 3),
            dispatch_bytes_ici_per_layer=int(wire),
            capacity_overflow_frac=round(
                overflow[-1] if overflow else 0.0, 4))

    def sec_fused_optimizer():
        # Fused blockwise Adam A/B (docs/PERFORMANCE.md "Kernel tier
        # round 2"): XLA elementwise chain vs the one-pass Pallas
        # kernel, same trajectory asserted inside. CPU rows time the
        # interpreter (informational); the HBM round-trip win is a TPU
        # round's claim.
        t0 = time.time()
        fo = bench_fused_optimizer()
        log(f"[bench] fused optimizer A/B (~1.05M params, 1 device): "
            f"xla {fo['optimizer_step_xla_ms']:.2f} ms vs fused "
            f"{fo['optimizer_step_fused_ms']:.2f} ms "
            f"({time.time() - t0:.0f}s)")
        for key, val in fo.items():
            result[key] = val
        _section_rows(result, "fused_optimizer", **fo)

    sections = [("bert128", sec_bert128)]
    if on_tpu:
        sections += [("bert512", sec_bert512), ("gpt2", sec_gpt2),
                     ("gpt2_dropout", sec_gpt2_dropout), ("long16k", sec_long),
                     ("inference", sec_inference)]
    sections += [("serving", sec_serving),
                 ("fused_optimizer", sec_fused_optimizer)]
    # The 2-slice overlap A/B needs an even multi-device split;
    # single-device CPU runs skip it (not a failure — no mesh to build).
    if n_chips_all >= 2 and n_chips_all % 2 == 0:
        sections += [("comm_overlap", sec_comm_overlap),
                     ("autotune", sec_autotune)]
    # The zeropp A/B additionally needs a data axis > 1 AND a
    # power-of-two chip count: on exactly 2 devices build_mesh(slices=2)
    # gives dcn=2 x data=1 (the hpZ gather axis is size 1), and an odd
    # data axis (6 devices -> data=3) divides none of tiny-GPT's
    # power-of-two dims — either way ParamGatherPlan gathers nothing and
    # the "on" row would baseline a noise-only no-op as a qwZ
    # measurement.
    if n_chips_all >= 4 and (n_chips_all & (n_chips_all - 1)) == 0:
        sections += [("zeropp", sec_zeropp)]
    # The MoE dispatch A/B needs an expert axis of 2 with a data axis
    # left over (>= 4 even chips); the all-to-all manual region also
    # wants the token count divisible by the full dispatch grid, which
    # the micro-batch choice above guarantees for even chip counts.
    if n_chips_all >= 4 and n_chips_all % 2 == 0:
        sections += [("moe_gpt", sec_moe_gpt)]
    n_ok = 0
    for name, fn in sections:
        n_ok += bool(run_section(name, fn, result))

    if result["value"] is None:
        # Headline fallback: if the BERT-128 section failed both attempts,
        # promote the best surviving row so `value` is never null while
        # data is present elsewhere.
        for vkey, bkey, metric, unit in (
                ("gpt2_tokens_per_sec", "gpt2_vs_baseline",
                 "GPT-2 seq512 ZeRO-2 pretrain throughput",
                 "tokens/sec/chip"),
                ("bert_seq512_samples_per_sec", "bert_seq512_vs_baseline",
                 "BERT-large seq512 ZeRO-2 pretrain throughput",
                 "samples/sec/chip"),
                ("gpt2_dropout_tokens_per_sec", None,
                 "GPT-2 seq512 dropout-on pretrain throughput",
                 "tokens/sec/chip"),
                ("gpt2_seq16k_dense_tokens_per_sec", None,
                 "GPT-2 seq16384 pretrain throughput", "tokens/sec/chip")):
            if result.get(vkey):
                result["metric"] = f"{metric} ({platform})"
                result["unit"] = unit
                result["value"] = result[vkey]
                result["vs_baseline"] = result[bkey] if bkey else None
                break

    _flush_partial(result)
    print(json.dumps(result))
    # Exit 0 iff ANY section produced a row: partial evidence is a valid
    # record, but a zero-row run must stay loudly distinguishable from
    # success in the driver's rc-based log (the round-4 rc=1 signal).
    sys.exit(0 if n_ok else 1)


if __name__ == "__main__":
    main()
